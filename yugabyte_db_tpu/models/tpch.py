"""TPC-H-style benchmark pipelines — the engine's "flagship models".

Implements the BASELINE.json benchmark configs: a lineitem-shaped table,
Q6 (predicate + SUM pushdown) and Q1 (GROUP BY aggregate pushdown),
runnable on the single-tablet CPU/TPU paths and the multi-tablet
distributed path (psum combine). Reference queries: TPC-H spec;
reference execution path being replaced: the DocDB scalar scan loop
(src/yb/docdb/pgsql_operation.cc:2790).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from ..docdb.table_codec import TableInfo
from ..dockv.packed_row import ColumnSchema, ColumnType, TableSchema
from ..dockv.partition import PartitionSchema
from ..ops import AggSpec, Expr
from ..ops.scan import GroupSpec

C = Expr.col

# column ids
ROWID, QTY, EXTPRICE, DISCOUNT, TAX, SHIPDATE, RETFLAG, LINESTATUS = range(8)

ROWS_PER_SF = 6_000_000


def lineitem_schema() -> TableSchema:
    return TableSchema(columns=(
        ColumnSchema(ROWID, "rowid", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(QTY, "l_quantity", ColumnType.FLOAT64),
        ColumnSchema(EXTPRICE, "l_extendedprice", ColumnType.FLOAT64),
        ColumnSchema(DISCOUNT, "l_discount", ColumnType.FLOAT64),
        ColumnSchema(TAX, "l_tax", ColumnType.FLOAT64),
        ColumnSchema(SHIPDATE, "l_shipdate", ColumnType.INT32),   # days
        ColumnSchema(RETFLAG, "l_returnflag", ColumnType.INT32),  # 0..2
        ColumnSchema(LINESTATUS, "l_linestatus", ColumnType.INT32),  # 0..1
    ), version=1)


def lineitem_info() -> TableInfo:
    return TableInfo("lineitem", "lineitem", lineitem_schema(),
                     PartitionSchema("hash", 1))


def lineitem_range_info() -> TableInfo:
    """Range-sharded lineitem clone: rowid is the range PK, so bulk
    loads land key-clustered by rowid and per-block zone maps give the
    scan pushdown real pruning power on rowid ranges (the hash-sharded
    layout scrambles rowid across blocks, which is exactly why the
    zone-prune bench uses this shape)."""
    cols = lineitem_schema().columns
    range_cols = (ColumnSchema(cols[0].id, cols[0].name, cols[0].type,
                               is_range_key=True),) + cols[1:]
    return TableInfo("lineitem_r", "lineitem_r",
                     TableSchema(columns=range_cols, version=1),
                     PartitionSchema("range", 0))


#: TPC-H's actual flag domains — the string-keyed lineitem variant maps
#: the synthetic int codes onto them so Q1's GROUP BY runs over real
#: dictionary-encoded string columns (the dict-key grouped kernel's
#: target shape)
RETFLAG_STRINGS = np.array(["A", "N", "R"], object)
LINESTATUS_STRINGS = np.array(["F", "O"], object)


def lineitem_str_info() -> TableInfo:
    """Range-sharded lineitem clone with STRING l_returnflag /
    l_linestatus (the TPC-H spec's actual types). Q1 over this shape is
    the dict-key grouped-aggregation benchmark: group keys ride as
    dictionary codes, the GROUP BY aggregates on device, and the
    interpreted row-at-a-time path is the flag-off baseline."""
    cols = lineitem_schema().columns
    str_cols = (ColumnSchema(cols[0].id, cols[0].name, cols[0].type,
                             is_range_key=True),) + cols[1:RETFLAG] + (
        ColumnSchema(RETFLAG, "l_returnflag", ColumnType.STRING),
        ColumnSchema(LINESTATUS, "l_linestatus", ColumnType.STRING),
    )
    return TableInfo("lineitem_s", "lineitem_s",
                     TableSchema(columns=str_cols, version=1),
                     PartitionSchema("range", 0))


def lineitem_str_data(data: Dict[str, np.ndarray]
                      ) -> Dict[str, np.ndarray]:
    """The same rows as `data` (generate_lineitem output) with the flag
    columns mapped onto their TPC-H string domains."""
    out = dict(data)
    out["l_returnflag"] = RETFLAG_STRINGS[data["l_returnflag"]]
    out["l_linestatus"] = LINESTATUS_STRINGS[data["l_linestatus"]]
    return out


def generate_lineitem(sf: float, seed: int = 0) -> Dict[str, np.ndarray]:
    """Synthetic lineitem with TPC-H-like distributions (uniforms per the
    spec's value ranges)."""
    n = int(ROWS_PER_SF * sf)
    rng = np.random.default_rng(seed)
    return {
        "rowid": np.arange(n, dtype=np.int64),
        "l_quantity": rng.integers(1, 51, n).astype(np.float64),
        "l_extendedprice": rng.uniform(900, 105000, n),
        "l_discount": rng.integers(0, 11, n).astype(np.float64) / 100.0,
        "l_tax": rng.integers(0, 9, n).astype(np.float64) / 100.0,
        "l_shipdate": rng.integers(8036, 10592, n).astype(np.int32),
        "l_returnflag": rng.integers(0, 3, n).astype(np.int32),
        "l_linestatus": rng.integers(0, 2, n).astype(np.int32),
    }


# TPC-H Q6: SELECT sum(l_extendedprice * l_discount) FROM lineitem WHERE
#   l_shipdate >= date '1994-01-01' AND l_shipdate < date '1995-01-01'
#   AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24
_D1994 = 8766       # days since epoch for 1994-01-01
_D1995 = 9131


@dataclass(frozen=True)
class QuerySpec:
    name: str
    where: Optional[tuple]
    aggs: Tuple[AggSpec, ...]
    group: Optional[GroupSpec]
    columns: Tuple[int, ...]


TPCH_Q6 = QuerySpec(
    name="q6",
    where=((C(SHIPDATE) >= _D1994) & (C(SHIPDATE) < _D1995)
           & C(DISCOUNT).between(0.05, 0.07) & (C(QTY) < 24.0)).node,
    aggs=(AggSpec("sum", (C(EXTPRICE) * C(DISCOUNT)).node),),
    group=None,
    columns=(QTY, EXTPRICE, DISCOUNT, SHIPDATE),
)

# TPC-H Q1: grouped sums over (returnflag, linestatus), shipdate <= cutoff
_Q1_CUT = 10471     # 1998-09-02

TPCH_Q1 = QuerySpec(
    name="q1",
    where=(C(SHIPDATE) <= _Q1_CUT).node,
    aggs=(
        AggSpec("sum", C(QTY).node),
        AggSpec("sum", C(EXTPRICE).node),
        AggSpec("sum", (C(EXTPRICE) * (Expr.const(1.0) - C(DISCOUNT))).node),
        AggSpec("sum", ((C(EXTPRICE) * (Expr.const(1.0) - C(DISCOUNT)))
                        * (Expr.const(1.0) + C(TAX))).node),
        AggSpec("count"),
    ),
    group=GroupSpec(cols=((RETFLAG, 3, 0), (LINESTATUS, 2, 0))),
    columns=(QTY, EXTPRICE, DISCOUNT, TAX, SHIPDATE, RETFLAG, LINESTATUS),
)


# Q1 over the string-keyed lineitem: identical WHERE and aggregate
# list, GROUP BY the two STRING flag columns through the dict-key
# grouped kernel (ops/grouped_scan.py). The 8-slot bucket (6 groups +
# spill) is the kernel's smallest shape above _MIN_SLOTS.
def tpch_q1_str() -> QuerySpec:
    from ..ops.grouped_scan import DictGroupSpec
    return QuerySpec(
        name="q1_str", where=TPCH_Q1.where, aggs=TPCH_Q1.aggs,
        group=DictGroupSpec(cols=(RETFLAG, LINESTATUS)),
        columns=TPCH_Q1.columns)


# ---------------------------------------------------------------------------
# Join workload (Q3/Q5-shaped): orders build side + orderkey'd lineitem
# ---------------------------------------------------------------------------

#: appended column id on the join-enabled lineitem clone
L_ORDERKEY = 8

O_ORDERKEY, O_ORDERDATE, O_PRIO = 0, 1, 2

#: TPC-H o_orderpriority domain — the string dimension attribute the
#: fused join+group plan groups by (dict-coded build payload)
PRIO_STRINGS = np.array(["1-URGENT", "2-HIGH", "3-MEDIUM",
                         "4-NOT SPECIFIED", "5-LOW"], object)

#: lineitems per order (the TPC-H fanout is 1..7, avg 4)
LINES_PER_ORDER = 4


def orders_schema() -> TableSchema:
    return TableSchema(columns=(
        ColumnSchema(O_ORDERKEY, "o_orderkey", ColumnType.INT64,
                     is_range_key=True),
        ColumnSchema(O_ORDERDATE, "o_orderdate", ColumnType.INT32),
        ColumnSchema(O_PRIO, "o_orderpriority", ColumnType.STRING),
    ), version=1)


def orders_info() -> TableInfo:
    return TableInfo("orders", "orders", orders_schema(),
                     PartitionSchema("range", 0))


def lineitem_join_info() -> TableInfo:
    """Range-sharded lineitem clone carrying the l_orderkey FK — the
    probe side of the fused join plans."""
    cols = lineitem_schema().columns
    jcols = (ColumnSchema(cols[0].id, cols[0].name, cols[0].type,
                          is_range_key=True),) + cols[1:] + (
        ColumnSchema(L_ORDERKEY, "l_orderkey", ColumnType.INT64),)
    return TableInfo("lineitem_j", "lineitem_j",
                     TableSchema(columns=jcols, version=1),
                     PartitionSchema("range", 0))


def generate_orders(n_orders: int, seed: int = 1
                    ) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    return {
        "o_orderkey": np.arange(n_orders, dtype=np.int64),
        "o_orderdate": rng.integers(8036, 10592, n_orders
                                    ).astype(np.int32),
        "o_orderpriority": PRIO_STRINGS[rng.integers(0, 5, n_orders)],
    }


def lineitem_join_data(data: Dict[str, np.ndarray],
                       n_orders: int) -> Dict[str, np.ndarray]:
    """`data` rows plus an l_orderkey FK: LINES_PER_ORDER consecutive
    lineitems share one order (clipped into the key domain)."""
    out = dict(data)
    out["l_orderkey"] = np.minimum(
        data["rowid"] // LINES_PER_ORDER,
        max(n_orders - 1, 0)).astype(np.int64)
    return out


@dataclass(frozen=True)
class JoinQuerySpec:
    """A fused filter->join->group->aggregate plan shape: probe-side
    WHERE over lineitem_j ids, a build-side orders filter (applied by
    the SENDER before shipping — inner-join semantics make build-side
    filtering equivalent to a post-join predicate), aggregates/group
    over probe ids + build payload ids (>= BUILD_COL_BASE)."""
    name: str
    probe_where: Optional[tuple]
    build_date_lo: int
    build_date_hi: int
    aggs: Tuple[AggSpec, ...]
    group: object
    probe_columns: Tuple[int, ...]


def prio_build_col() -> int:
    from ..ops.join_scan import BUILD_COL_BASE
    return BUILD_COL_BASE


#: one quarter of o_orderdate — keeps the shipped build side small
#: (the dimension-side contract of the join pushdown)
_Q3_LO, _Q3_HI = _D1994, _D1994 + 91


def tpch_q3ish() -> JoinQuerySpec:
    """Q3/Q5-shaped: revenue by o_orderpriority over one order
    quarter.  SELECT o_orderpriority, sum(l_extendedprice *
    (1 - l_discount)), count(*) FROM lineitem JOIN orders ON
    l_orderkey = o_orderkey WHERE l_shipdate >= 1994-01-01 AND
    o_orderdate in the quarter GROUP BY o_orderpriority."""
    from ..ops.grouped_scan import DictGroupSpec
    return JoinQuerySpec(
        name="q3ish",
        probe_where=(C(SHIPDATE) >= _D1994).node,
        build_date_lo=_Q3_LO, build_date_hi=_Q3_HI,
        aggs=(AggSpec("sum", (C(EXTPRICE)
                              * (Expr.const(1.0) - C(DISCOUNT))).node),
              AggSpec("count")),
        group=DictGroupSpec(cols=(prio_build_col(),)),
        probe_columns=(EXTPRICE, DISCOUNT, SHIPDATE, L_ORDERKEY),
    )


def orders_build_wire(q: JoinQuerySpec, odata: Dict[str, np.ndarray]):
    """The shipped build side for `q`: orders keys inside the date
    window + the o_orderpriority payload column."""
    from ..ops.join_scan import JoinWire
    m = ((odata["o_orderdate"] >= q.build_date_lo)
         & (odata["o_orderdate"] < q.build_date_hi))
    return JoinWire(
        probe_col=L_ORDERKEY,
        keys=odata["o_orderkey"][m],
        payload={prio_build_col(): (odata["o_orderpriority"][m],
                                    None)})


def numpy_reference_join(q: JoinQuerySpec,
                         ldata: Dict[str, np.ndarray],
                         odata: Dict[str, np.ndarray]):
    """{o_orderpriority: (count, revenue)} straight from numpy."""
    ok = ldata["l_orderkey"]
    od = odata["o_orderdate"][ok]
    m = ((ldata["l_shipdate"] >= _D1994)
         & (od >= q.build_date_lo) & (od < q.build_date_hi))
    prio = odata["o_orderpriority"][ok]
    rev = ldata["l_extendedprice"] * (1.0 - ldata["l_discount"])
    out = {}
    for p in PRIO_STRINGS:
        mg = m & (prio == p)
        out[p] = (int(mg.sum()), float(rev[mg].sum()))
    return out


# ---------------------------------------------------------------------------
# Whole-query gauntlet: customer dimension + multi-join chains + the
# 22-query TPC-H registry (runnable adapted specs or TYPED inexpressible
# reasons — a query the engine cannot serve is named, never silent)
# ---------------------------------------------------------------------------

C_CUSTKEY, C_MKTSEGMENT, C_NATION = 0, 1, 2

#: appended column id on the orders_c clone (the chain FK to customer)
O_CUSTKEY = 3

#: TPC-H spec cardinalities per scale factor
ORDERS_PER_SF = 1_500_000
CUSTOMERS_PER_SF = 150_000

MKTSEG_STRINGS = np.array(
    ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"],
    object)

NATION_STRINGS = np.array(
    ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "CHINA", "EGYPT",
     "ETHIOPIA", "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN",
     "IRAQ", "JAPAN", "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE",
     "PERU", "ROMANIA", "RUSSIA", "SAUDI ARABIA", "UNITED KINGDOM",
     "UNITED STATES", "VIETNAM"], object)


def customer_schema() -> TableSchema:
    return TableSchema(columns=(
        ColumnSchema(C_CUSTKEY, "c_custkey", ColumnType.INT64,
                     is_range_key=True),
        ColumnSchema(C_MKTSEGMENT, "c_mktsegment", ColumnType.STRING),
        ColumnSchema(C_NATION, "c_nation", ColumnType.STRING),
    ), version=1)


def customer_info() -> TableInfo:
    return TableInfo("customer", "customer", customer_schema(),
                     PartitionSchema("range", 0))


def orders_cust_schema() -> TableSchema:
    """orders + the o_custkey FK — the middle table of the 3-table
    chain (lineitem -> orders_c -> customer).  A separate clone so the
    2-table workloads keep their original schema/signature."""
    return TableSchema(columns=orders_schema().columns + (
        ColumnSchema(O_CUSTKEY, "o_custkey", ColumnType.INT64),),
        version=1)


def orders_cust_info() -> TableInfo:
    return TableInfo("orders_c", "orders_c", orders_cust_schema(),
                     PartitionSchema("range", 0))


def generate_customer(n_customers: int, seed: int = 2
                      ) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    return {
        "c_custkey": np.arange(n_customers, dtype=np.int64),
        "c_mktsegment": MKTSEG_STRINGS[rng.integers(0, len(MKTSEG_STRINGS),
                                                    n_customers)],
        "c_nation": NATION_STRINGS[rng.integers(0, len(NATION_STRINGS),
                                                n_customers)],
    }


def generate_orders_cust(n_orders: int, n_customers: int, seed: int = 1
                         ) -> Dict[str, np.ndarray]:
    out = generate_orders(n_orders, seed)
    rng = np.random.default_rng(seed + 7)
    out["o_custkey"] = rng.integers(0, max(n_customers, 1),
                                    n_orders).astype(np.int64)
    return out


def chain_bids() -> Dict[str, int]:
    """Fixed payload-lane ids for the lineitem->orders_c->customer
    chain (one shared BUILD_COL_BASE counter, as the executor's
    lowering pass assigns them)."""
    from ..ops.join_scan import BUILD_COL_BASE
    return {"o_custkey": BUILD_COL_BASE,
            "o_orderpriority": BUILD_COL_BASE + 1,
            "c_mktsegment": BUILD_COL_BASE + 2,
            "c_nation": BUILD_COL_BASE + 3}


@dataclass(frozen=True)
class ChainQuerySpec:
    """A 3-table fused chain: lineitem_j probes orders_c (stage 0, by
    l_orderkey), then the o_custkey payload LANE probes customer
    (stage 1) — one device program, one shared visibility mask.
    Build-side filters (order date window, customer segment) are
    applied by the sender; inner-join semantics make that equivalent to
    a post-join predicate."""
    name: str
    probe_where: Optional[tuple]
    order_date_lo: Optional[int]
    order_date_hi: Optional[int]
    cust_seg: Optional[str]
    order_payload: Tuple[str, ...]      # extra stage-0 payload names
    cust_payload: Tuple[str, ...]       # stage-1 payload names
    group_col: str                      # payload name the group rides on
    aggs: Tuple[AggSpec, ...]
    probe_columns: Tuple[int, ...]


#: Q3's cutoff date (1995-03-15)
_Q3_CUT = 9204


def _chain_group(group_col: str):
    from ..ops.grouped_scan import DictGroupSpec
    return DictGroupSpec(cols=(chain_bids()[group_col],))


_REV = AggSpec("sum", (C(EXTPRICE) * (Expr.const(1.0)
                                      - C(DISCOUNT))).node)


def tpch_q3_chain() -> ChainQuerySpec:
    """Q3 adapted: revenue by o_orderpriority for BUILDING-segment
    customers, o_orderdate < 1995-03-15 < l_shipdate.  The spec's
    GROUP BY l_orderkey (a 1.5M/SF domain) is lowered to the
    dict-coded priority dimension — group_domain is the typed reason
    the literal shape refuses."""
    return ChainQuerySpec(
        name="q3", probe_where=(C(SHIPDATE) > _Q3_CUT).node,
        order_date_lo=None, order_date_hi=_Q3_CUT,
        cust_seg="BUILDING",
        order_payload=("o_orderpriority",), cust_payload=(),
        group_col="o_orderpriority",
        aggs=(_REV, AggSpec("count")),
        probe_columns=(EXTPRICE, DISCOUNT, SHIPDATE, L_ORDERKEY))


def tpch_q5_chain() -> ChainQuerySpec:
    """Q5 adapted: 1994 revenue by customer nation.  The supplier/
    nation/region legs are dropped (table_coverage) — nation rides as
    a denormalized customer attribute."""
    return ChainQuerySpec(
        name="q5", probe_where=None,
        order_date_lo=_D1994, order_date_hi=_D1995,
        cust_seg=None,
        order_payload=(), cust_payload=("c_nation",),
        group_col="c_nation",
        aggs=(_REV, AggSpec("count")),
        probe_columns=(EXTPRICE, DISCOUNT, L_ORDERKEY))


def tpch_q10_chain() -> ChainQuerySpec:
    """Q10 adapted: returned-item (l_returnflag = 'R') revenue by
    customer nation over one order quarter.  GROUP BY c_custkey
    (150k/SF domain, top-20) is lowered to c_nation — group_domain is
    the typed reason the literal shape refuses."""
    return ChainQuerySpec(
        name="q10",
        probe_where=C(RETFLAG).eq(
            int(np.flatnonzero(RETFLAG_STRINGS == "R")[0])).node,
        order_date_lo=_D1994, order_date_hi=_D1994 + 91,
        cust_seg=None,
        order_payload=(), cust_payload=("c_nation",),
        group_col="c_nation",
        aggs=(_REV, AggSpec("count")),
        probe_columns=(EXTPRICE, DISCOUNT, RETFLAG, L_ORDERKEY))


def chain_build_wires(q: ChainQuerySpec,
                      odata: Dict[str, np.ndarray],
                      cdata: Dict[str, np.ndarray]):
    """The ordered 2-stage JoinWire list for `q` (probe order IS the
    list order): filtered orders_c keyed by o_orderkey shipping the
    o_custkey lane, then filtered customer keyed by c_custkey probed
    THROUGH that lane."""
    from ..ops.join_scan import JoinWire
    bids = chain_bids()
    mo = np.ones(len(odata["o_orderkey"]), bool)
    if q.order_date_lo is not None:
        mo &= odata["o_orderdate"] >= q.order_date_lo
    if q.order_date_hi is not None:
        mo &= odata["o_orderdate"] < q.order_date_hi
    opay = {bids["o_custkey"]: (odata["o_custkey"][mo], None)}
    for nm in q.order_payload:
        opay[bids[nm]] = (odata[nm][mo], None)
    mc = np.ones(len(cdata["c_custkey"]), bool)
    if q.cust_seg is not None:
        mc &= cdata["c_mktsegment"] == q.cust_seg
    cpay = {bids[nm]: (cdata[nm][mc], None) for nm in q.cust_payload}
    return (JoinWire(probe_col=L_ORDERKEY,
                     keys=odata["o_orderkey"][mo], payload=opay),
            JoinWire(probe_col=bids["o_custkey"],
                     keys=cdata["c_custkey"][mc], payload=cpay))


def numpy_reference_chain(q: ChainQuerySpec,
                          ldata: Dict[str, np.ndarray],
                          odata: Dict[str, np.ndarray],
                          cdata: Dict[str, np.ndarray]):
    """{group string: (count, revenue)} straight from numpy."""
    ok = ldata["l_orderkey"]
    ck = odata["o_custkey"][ok]
    m = np.ones(len(ok), bool)
    if q.name == "q3":
        m &= ldata["l_shipdate"] > _Q3_CUT
    elif q.name == "q10":
        m &= (ldata["l_returnflag"]
              == int(np.flatnonzero(RETFLAG_STRINGS == "R")[0]))
    od = odata["o_orderdate"][ok]
    if q.order_date_lo is not None:
        m &= od >= q.order_date_lo
    if q.order_date_hi is not None:
        m &= od < q.order_date_hi
    if q.cust_seg is not None:
        m &= cdata["c_mktsegment"][ck] == q.cust_seg
    gvals = (odata[q.group_col][ok] if q.group_col.startswith("o_")
             else cdata[q.group_col][ck])
    rev = ldata["l_extendedprice"] * (1.0 - ldata["l_discount"])
    domain = (PRIO_STRINGS if q.group_col == "o_orderpriority"
              else NATION_STRINGS if q.group_col == "c_nation"
              else MKTSEG_STRINGS)
    out = {}
    for g in domain:
        mg = m & (gvals == g)
        out[g] = (int(mg.sum()), float(rev[mg].sum()))
    return out


# --- the 22-query registry -------------------------------------------------

#: typed reasons a TPC-H query is inexpressible on this engine — the
#: gauntlet reports these per query, never a silent skip
REASON_TABLE_COVERAGE = "table_coverage"    # part/supplier/partsupp/
                                            # nation/region not modeled
REASON_SUBQUERY = "subquery_shape"          # correlated/scalar subquery
REASON_SEMI_JOIN = "semi_join"              # EXISTS / NOT EXISTS
REASON_OUTER_JOIN = "outer_join"            # LEFT OUTER JOIN
REASON_GROUP_DOMAIN = "group_domain"        # group key domain too wide
REASON_EXPR_SHAPE = "expr_shape"            # CASE/LIKE/substring aggs


@dataclass(frozen=True)
class TpchEntry:
    """One TPC-H query in the gauntlet: `kind` is scan/join/chain with
    a runnable (possibly adapted) spec, or "inexpressible" with a typed
    `reason`.  `note` records the adaptation or the refusal detail."""
    name: str
    kind: str                   # "scan" | "join" | "chain" | "inexpressible"
    note: str
    spec: object = None
    reason: Optional[str] = None


def tpch_queries() -> Dict[str, TpchEntry]:
    """All 22 TPC-H queries, in order.  Runnable entries carry a spec
    for the device path; the rest carry a typed refusal reason."""
    E = TpchEntry
    return {e.name: e for e in (
        E("q1", "scan", "pricing summary — dict-key GROUP BY over the "
          "STRING flag columns", tpch_q1_str()),
        E("q2", "inexpressible", "min-cost supplier: part/supplier/"
          "partsupp/nation/region + correlated MIN subquery",
          reason=REASON_TABLE_COVERAGE),
        E("q3", "chain", "shipping priority — GROUP BY l_orderkey "
          "(1.5M/SF domain) lowered to o_orderpriority",
          tpch_q3_chain()),
        E("q4", "inexpressible", "order priority checking: EXISTS "
          "semi-join counting ORDERS, not lineitems",
          reason=REASON_SEMI_JOIN),
        E("q5", "chain", "local supplier volume — supplier/nation/"
          "region legs dropped; nation rides on customer",
          tpch_q5_chain()),
        E("q6", "scan", "forecasting revenue change — literal",
          TPCH_Q6),
        E("q7", "inexpressible", "volume shipping: supplier + nation "
          "pair (supp_nation, cust_nation) not modeled",
          reason=REASON_TABLE_COVERAGE),
        E("q8", "inexpressible", "national market share: 8-table join "
          "over part/supplier/nation/region",
          reason=REASON_TABLE_COVERAGE),
        E("q9", "inexpressible", "product type profit: part/supplier/"
          "partsupp not modeled", reason=REASON_TABLE_COVERAGE),
        E("q10", "chain", "returned items — GROUP BY c_custkey "
          "(150k/SF, top-20) lowered to c_nation", tpch_q10_chain()),
        E("q11", "inexpressible", "important stock: partsupp/supplier/"
          "nation + HAVING scalar subquery",
          reason=REASON_TABLE_COVERAGE),
        E("q12", "inexpressible", "shipping modes: CASE conditional "
          "aggregates; l_shipmode/commitdate/receiptdate not modeled",
          reason=REASON_EXPR_SHAPE),
        E("q13", "inexpressible", "customer distribution: LEFT OUTER "
          "JOIN + group-over-count", reason=REASON_OUTER_JOIN),
        E("q14", "inexpressible", "promotion effect: part + LIKE-"
          "guarded conditional aggregate", reason=REASON_EXPR_SHAPE),
        E("q15", "inexpressible", "top supplier: supplier + view with "
          "scalar MAX subquery", reason=REASON_SUBQUERY),
        E("q16", "inexpressible", "parts/supplier relationship: part/"
          "partsupp + COUNT DISTINCT", reason=REASON_TABLE_COVERAGE),
        E("q17", "inexpressible", "small-quantity-order revenue: "
          "correlated AVG subquery per part", reason=REASON_SUBQUERY),
        E("q18", "inexpressible", "large volume customer: HAVING "
          "SUM(qty) subquery over the 1.5M/SF orderkey domain",
          reason=REASON_SUBQUERY),
        E("q19", "inexpressible", "discounted revenue: part table not "
          "modeled (the OR-of-triples predicate itself is "
          "expressible)", reason=REASON_TABLE_COVERAGE),
        E("q20", "inexpressible", "potential part promotion: nested "
          "IN subqueries over part/partsupp/supplier",
          reason=REASON_SUBQUERY),
        E("q21", "inexpressible", "suppliers who kept orders waiting: "
          "supplier + EXISTS/NOT EXISTS self-joins",
          reason=REASON_SEMI_JOIN),
        E("q22", "inexpressible", "global sales opportunity: "
          "substring() + NOT EXISTS + scalar AVG subquery",
          reason=REASON_SUBQUERY),
    )}


def numpy_reference(query: QuerySpec, data: Dict[str, np.ndarray]):
    """Direct numpy answer for verification."""
    qty, price, disc = (data["l_quantity"], data["l_extendedprice"],
                        data["l_discount"])
    if query.name == "q6":
        m = ((data["l_shipdate"] >= _D1994) & (data["l_shipdate"] < _D1995)
             & (disc >= 0.05) & (disc <= 0.07) & (qty < 24.0))
        return (price[m] * disc[m]).sum()
    if query.name == "q1":
        m = data["l_shipdate"] <= _Q1_CUT
        gid = data["l_returnflag"] + 3 * data["l_linestatus"]
        out = {}
        for g in range(6):
            mg = m & (gid == g)
            out[g] = (qty[mg].sum(), price[mg].sum(), int(mg.sum()))
        return out
    if query.name == "q1_str":
        # {(returnflag, linestatus) strings: (qty_sum, price_sum, count)}
        # — accepts int-coded OR string flag columns
        rf, ls = data["l_returnflag"], data["l_linestatus"]
        if rf.dtype != object:
            rf, ls = RETFLAG_STRINGS[rf], LINESTATUS_STRINGS[ls]
        m = data["l_shipdate"] <= _Q1_CUT
        out = {}
        for rv in RETFLAG_STRINGS:
            for lv in LINESTATUS_STRINGS:
                mg = m & (rf == rv) & (ls == lv)
                out[(rv, lv)] = (qty[mg].sum(), price[mg].sum(),
                                 int(mg.sum()))
        return out
    raise ValueError(query.name)


class LineitemTable:
    """Helper owning a set of tablets covering the lineitem table."""

    def __init__(self, base_dir: str, num_tablets: int = 1, clock=None):
        from ..tablet import Tablet
        self.info = lineitem_info()
        parts = self.info.partition_schema.create_partitions(num_tablets)
        self.tablets = [
            Tablet(f"lineitem-{i}", self.info, f"{base_dir}/tablet-{i}",
                   clock=clock, partition=p)
            for i, p in enumerate(parts)]

    def load(self, data: Dict[str, np.ndarray], block_rows: int = 262144
             ) -> int:
        return sum(t.bulk_load(data, block_rows=block_rows)
                   for t in self.tablets)

    def read_request(self, query: QuerySpec, read_ht=None):
        from ..docdb.operations import ReadRequest
        return ReadRequest(
            "lineitem", where=query.where, aggregates=query.aggs,
            group_by=query.group, read_ht=read_ht)

    def run(self, query: QuerySpec, read_ht=None):
        """Execute across all tablets, combining partials host-side (the
        single-process analog of the client-side combine)."""
        from ..docdb.operations import ReadRequest
        total = None
        counts = None
        for t in self.tablets:
            resp = t.read(self.read_request(query, read_ht))
            vals = [np.asarray(v) for v in resp.agg_values]
            if total is None:
                total = vals
                counts = np.asarray(resp.group_counts) \
                    if resp.group_counts is not None else None
            else:
                for i, a in enumerate(_expanded(query.aggs)):
                    if a.op in ("sum", "count"):
                        total[i] = total[i] + vals[i]
                    elif a.op == "min":
                        total[i] = np.minimum(total[i], vals[i])
                    else:
                        total[i] = np.maximum(total[i], vals[i])
                if counts is not None:
                    counts = counts + np.asarray(resp.group_counts)
        return total, counts


def _expanded(aggs):
    from ..ops.scan import _expand_avg
    return _expand_avg(aggs)
