"""TPC-H-style benchmark pipelines — the engine's "flagship models".

Implements the BASELINE.json benchmark configs: a lineitem-shaped table,
Q6 (predicate + SUM pushdown) and Q1 (GROUP BY aggregate pushdown),
runnable on the single-tablet CPU/TPU paths and the multi-tablet
distributed path (psum combine). Reference queries: TPC-H spec;
reference execution path being replaced: the DocDB scalar scan loop
(src/yb/docdb/pgsql_operation.cc:2790).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from ..docdb.table_codec import TableInfo
from ..dockv.packed_row import ColumnSchema, ColumnType, TableSchema
from ..dockv.partition import PartitionSchema
from ..ops import AggSpec, Expr
from ..ops.scan import GroupSpec

C = Expr.col

# column ids
ROWID, QTY, EXTPRICE, DISCOUNT, TAX, SHIPDATE, RETFLAG, LINESTATUS = range(8)

ROWS_PER_SF = 6_000_000


def lineitem_schema() -> TableSchema:
    return TableSchema(columns=(
        ColumnSchema(ROWID, "rowid", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(QTY, "l_quantity", ColumnType.FLOAT64),
        ColumnSchema(EXTPRICE, "l_extendedprice", ColumnType.FLOAT64),
        ColumnSchema(DISCOUNT, "l_discount", ColumnType.FLOAT64),
        ColumnSchema(TAX, "l_tax", ColumnType.FLOAT64),
        ColumnSchema(SHIPDATE, "l_shipdate", ColumnType.INT32),   # days
        ColumnSchema(RETFLAG, "l_returnflag", ColumnType.INT32),  # 0..2
        ColumnSchema(LINESTATUS, "l_linestatus", ColumnType.INT32),  # 0..1
    ), version=1)


def lineitem_info() -> TableInfo:
    return TableInfo("lineitem", "lineitem", lineitem_schema(),
                     PartitionSchema("hash", 1))


def lineitem_range_info() -> TableInfo:
    """Range-sharded lineitem clone: rowid is the range PK, so bulk
    loads land key-clustered by rowid and per-block zone maps give the
    scan pushdown real pruning power on rowid ranges (the hash-sharded
    layout scrambles rowid across blocks, which is exactly why the
    zone-prune bench uses this shape)."""
    cols = lineitem_schema().columns
    range_cols = (ColumnSchema(cols[0].id, cols[0].name, cols[0].type,
                               is_range_key=True),) + cols[1:]
    return TableInfo("lineitem_r", "lineitem_r",
                     TableSchema(columns=range_cols, version=1),
                     PartitionSchema("range", 0))


#: TPC-H's actual flag domains — the string-keyed lineitem variant maps
#: the synthetic int codes onto them so Q1's GROUP BY runs over real
#: dictionary-encoded string columns (the dict-key grouped kernel's
#: target shape)
RETFLAG_STRINGS = np.array(["A", "N", "R"], object)
LINESTATUS_STRINGS = np.array(["F", "O"], object)


def lineitem_str_info() -> TableInfo:
    """Range-sharded lineitem clone with STRING l_returnflag /
    l_linestatus (the TPC-H spec's actual types). Q1 over this shape is
    the dict-key grouped-aggregation benchmark: group keys ride as
    dictionary codes, the GROUP BY aggregates on device, and the
    interpreted row-at-a-time path is the flag-off baseline."""
    cols = lineitem_schema().columns
    str_cols = (ColumnSchema(cols[0].id, cols[0].name, cols[0].type,
                             is_range_key=True),) + cols[1:RETFLAG] + (
        ColumnSchema(RETFLAG, "l_returnflag", ColumnType.STRING),
        ColumnSchema(LINESTATUS, "l_linestatus", ColumnType.STRING),
    )
    return TableInfo("lineitem_s", "lineitem_s",
                     TableSchema(columns=str_cols, version=1),
                     PartitionSchema("range", 0))


def lineitem_str_data(data: Dict[str, np.ndarray]
                      ) -> Dict[str, np.ndarray]:
    """The same rows as `data` (generate_lineitem output) with the flag
    columns mapped onto their TPC-H string domains."""
    out = dict(data)
    out["l_returnflag"] = RETFLAG_STRINGS[data["l_returnflag"]]
    out["l_linestatus"] = LINESTATUS_STRINGS[data["l_linestatus"]]
    return out


def generate_lineitem(sf: float, seed: int = 0) -> Dict[str, np.ndarray]:
    """Synthetic lineitem with TPC-H-like distributions (uniforms per the
    spec's value ranges)."""
    n = int(ROWS_PER_SF * sf)
    rng = np.random.default_rng(seed)
    return {
        "rowid": np.arange(n, dtype=np.int64),
        "l_quantity": rng.integers(1, 51, n).astype(np.float64),
        "l_extendedprice": rng.uniform(900, 105000, n),
        "l_discount": rng.integers(0, 11, n).astype(np.float64) / 100.0,
        "l_tax": rng.integers(0, 9, n).astype(np.float64) / 100.0,
        "l_shipdate": rng.integers(8036, 10592, n).astype(np.int32),
        "l_returnflag": rng.integers(0, 3, n).astype(np.int32),
        "l_linestatus": rng.integers(0, 2, n).astype(np.int32),
    }


# TPC-H Q6: SELECT sum(l_extendedprice * l_discount) FROM lineitem WHERE
#   l_shipdate >= date '1994-01-01' AND l_shipdate < date '1995-01-01'
#   AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24
_D1994 = 8766       # days since epoch for 1994-01-01
_D1995 = 9131


@dataclass(frozen=True)
class QuerySpec:
    name: str
    where: Optional[tuple]
    aggs: Tuple[AggSpec, ...]
    group: Optional[GroupSpec]
    columns: Tuple[int, ...]


TPCH_Q6 = QuerySpec(
    name="q6",
    where=((C(SHIPDATE) >= _D1994) & (C(SHIPDATE) < _D1995)
           & C(DISCOUNT).between(0.05, 0.07) & (C(QTY) < 24.0)).node,
    aggs=(AggSpec("sum", (C(EXTPRICE) * C(DISCOUNT)).node),),
    group=None,
    columns=(QTY, EXTPRICE, DISCOUNT, SHIPDATE),
)

# TPC-H Q1: grouped sums over (returnflag, linestatus), shipdate <= cutoff
_Q1_CUT = 10471     # 1998-09-02

TPCH_Q1 = QuerySpec(
    name="q1",
    where=(C(SHIPDATE) <= _Q1_CUT).node,
    aggs=(
        AggSpec("sum", C(QTY).node),
        AggSpec("sum", C(EXTPRICE).node),
        AggSpec("sum", (C(EXTPRICE) * (Expr.const(1.0) - C(DISCOUNT))).node),
        AggSpec("sum", ((C(EXTPRICE) * (Expr.const(1.0) - C(DISCOUNT)))
                        * (Expr.const(1.0) + C(TAX))).node),
        AggSpec("count"),
    ),
    group=GroupSpec(cols=((RETFLAG, 3, 0), (LINESTATUS, 2, 0))),
    columns=(QTY, EXTPRICE, DISCOUNT, TAX, SHIPDATE, RETFLAG, LINESTATUS),
)


# Q1 over the string-keyed lineitem: identical WHERE and aggregate
# list, GROUP BY the two STRING flag columns through the dict-key
# grouped kernel (ops/grouped_scan.py). The 8-slot bucket (6 groups +
# spill) is the kernel's smallest shape above _MIN_SLOTS.
def tpch_q1_str() -> QuerySpec:
    from ..ops.grouped_scan import DictGroupSpec
    return QuerySpec(
        name="q1_str", where=TPCH_Q1.where, aggs=TPCH_Q1.aggs,
        group=DictGroupSpec(cols=(RETFLAG, LINESTATUS)),
        columns=TPCH_Q1.columns)


def numpy_reference(query: QuerySpec, data: Dict[str, np.ndarray]):
    """Direct numpy answer for verification."""
    qty, price, disc = (data["l_quantity"], data["l_extendedprice"],
                        data["l_discount"])
    if query.name == "q6":
        m = ((data["l_shipdate"] >= _D1994) & (data["l_shipdate"] < _D1995)
             & (disc >= 0.05) & (disc <= 0.07) & (qty < 24.0))
        return (price[m] * disc[m]).sum()
    if query.name == "q1":
        m = data["l_shipdate"] <= _Q1_CUT
        gid = data["l_returnflag"] + 3 * data["l_linestatus"]
        out = {}
        for g in range(6):
            mg = m & (gid == g)
            out[g] = (qty[mg].sum(), price[mg].sum(), int(mg.sum()))
        return out
    if query.name == "q1_str":
        # {(returnflag, linestatus) strings: (qty_sum, price_sum, count)}
        # — accepts int-coded OR string flag columns
        rf, ls = data["l_returnflag"], data["l_linestatus"]
        if rf.dtype != object:
            rf, ls = RETFLAG_STRINGS[rf], LINESTATUS_STRINGS[ls]
        m = data["l_shipdate"] <= _Q1_CUT
        out = {}
        for rv in RETFLAG_STRINGS:
            for lv in LINESTATUS_STRINGS:
                mg = m & (rf == rv) & (ls == lv)
                out[(rv, lv)] = (qty[mg].sum(), price[mg].sum(),
                                 int(mg.sum()))
        return out
    raise ValueError(query.name)


class LineitemTable:
    """Helper owning a set of tablets covering the lineitem table."""

    def __init__(self, base_dir: str, num_tablets: int = 1, clock=None):
        from ..tablet import Tablet
        self.info = lineitem_info()
        parts = self.info.partition_schema.create_partitions(num_tablets)
        self.tablets = [
            Tablet(f"lineitem-{i}", self.info, f"{base_dir}/tablet-{i}",
                   clock=clock, partition=p)
            for i, p in enumerate(parts)]

    def load(self, data: Dict[str, np.ndarray], block_rows: int = 262144
             ) -> int:
        return sum(t.bulk_load(data, block_rows=block_rows)
                   for t in self.tablets)

    def read_request(self, query: QuerySpec, read_ht=None):
        from ..docdb.operations import ReadRequest
        return ReadRequest(
            "lineitem", where=query.where, aggregates=query.aggs,
            group_by=query.group, read_ht=read_ht)

    def run(self, query: QuerySpec, read_ht=None):
        """Execute across all tablets, combining partials host-side (the
        single-process analog of the client-side combine)."""
        from ..docdb.operations import ReadRequest
        total = None
        counts = None
        for t in self.tablets:
            resp = t.read(self.read_request(query, read_ht))
            vals = [np.asarray(v) for v in resp.agg_values]
            if total is None:
                total = vals
                counts = np.asarray(resp.group_counts) \
                    if resp.group_counts is not None else None
            else:
                for i, a in enumerate(_expanded(query.aggs)):
                    if a.op in ("sum", "count"):
                        total[i] = total[i] + vals[i]
                    elif a.op == "min":
                        total[i] = np.minimum(total[i], vals[i])
                    else:
                        total[i] = np.maximum(total[i], vals[i])
                if counts is not None:
                    counts = counts + np.asarray(resp.group_counts)
        return total, counts


def _expanded(aggs):
    from ..ops.scan import _expand_avg
    return _expand_avg(aggs)
