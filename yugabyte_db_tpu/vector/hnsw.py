"""HNSW graph index: the recall-frontier host-path twin of the IVF.

Pure numpy (adjacency rectangles, no pointer soup): per level an
[capacity, width] int32 neighbor table (-1 padded), greedy layered
descent from the top entry point, and a classic best-first beam at the
base layer bounded by ``ef_search`` (reference: src/yb/hnsw/hnsw.cc and
the usearch wrapper in src/yb/ann_methods/usearch_wrapper.cc; algorithm
per Malkov & Yashunin).  Graph walks are a poor fit for the MXU — this
engine exists for the host path, where it owns the high-recall/low-qps
end of the frontier while the two-stage IVF owns the GEMM-shaped end.

Build is incremental by construction: ``add`` inserts with a beam of
``ef_construction`` candidates per layer, so the tablet's delta folds
become true inserts instead of full rebuilds.  Neighbor selection is
closest-M with reverse-link pruning to the level width (the simple
variant; the diversity heuristic is a knob we can add when real
clustered workloads demand it).
"""
from __future__ import annotations

import heapq
import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from .registry import AnnIndex, register_index


@register_index("hnsw")
class HnswIndex(AnnIndex):
    #: adjacency width: base layer gets 2*m (hnswlib's M_max0)
    def __init__(self, dim: int, m: int = 16, ef_construction: int = 100,
                 ef_search: int = 64, seed: int = 0,
                 options: Optional[dict] = None):
        self._dim = int(dim)
        self.m = int(m)
        self.m0 = 2 * self.m
        self.ef_construction = int(ef_construction)
        self.ef_search = int(ef_search)
        self._ml = 1.0 / math.log(max(2, self.m))
        self._rng = np.random.default_rng(seed)
        self.options = dict(options or {},
                            m=self.m, ef_construction=self.ef_construction,
                            ef_search=self.ef_search)
        cap = 1024
        self.vecs = np.zeros((cap, self._dim), np.float32)
        self.norms = np.zeros(cap, np.float32)
        self.levels = np.full(cap, -1, np.int8)
        self._adj: List[np.ndarray] = [np.full((cap, self.m0), -1,
                                               np.int32)]
        self._n = 0
        self._ep = -1            # entry point node id
        self._max_level = 0

    # ---- construction ----------------------------------------------------
    @classmethod
    def build(cls, data: np.ndarray, m: int = 16,
              ef_construction: int = 100, ef_search: int = 64,
              seed: int = 0, **extra) -> "HnswIndex":
        data = np.asarray(data, np.float32)
        d = data.shape[1] if data.ndim == 2 and data.shape[1] else 1
        idx = cls(d, m=m, ef_construction=ef_construction,
                  ef_search=ef_search, seed=seed, options=extra)
        if len(data):
            idx.add(data)
        return idx

    def _grow(self, need: int) -> None:
        cap = len(self.vecs)
        if need <= cap:
            return
        while cap < need:
            cap *= 2
        for name in ("vecs", "norms", "levels"):
            old = getattr(self, name)
            shape = (cap,) + old.shape[1:]
            new = (np.full(shape, -1, old.dtype) if name == "levels"
                   else np.zeros(shape, old.dtype))
            new[:len(old)] = old
            setattr(self, name, new)
        for l, adj in enumerate(self._adj):
            new = np.full((cap, adj.shape[1]), -1, np.int32)
            new[:len(adj)] = adj
            self._adj[l] = new

    def _level_adj(self, level: int) -> np.ndarray:
        while level >= len(self._adj):
            self._adj.append(np.full((len(self.vecs), self.m), -1,
                                     np.int32))
        return self._adj[level]

    def add(self, vectors: np.ndarray) -> None:
        vectors = np.asarray(vectors, np.float32)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        self._grow(self._n + len(vectors))
        for v in vectors:
            self._insert(v)

    def _insert(self, v: np.ndarray) -> None:
        nid = self._n
        self.vecs[nid] = v
        self.norms[nid] = float(v @ v)
        lvl = int(-math.log(max(self._rng.random(), 1e-12)) * self._ml)
        self.levels[nid] = lvl
        self._n += 1
        if self._ep < 0:
            self._ep = nid
            self._max_level = lvl
            self._level_adj(lvl)     # materialize levels up front
            return
        ep = [self._ep]
        # zoom down through levels above the new node's level
        for l in range(self._max_level, lvl, -1):
            ep = self._greedy_step(v, ep[0], l)
        for l in range(min(self._max_level, lvl), -1, -1):
            cand = self._search_layer(v, ep, self.ef_construction, l)
            width = self.m0 if l == 0 else self.m
            sel = [i for _, i in cand[:self.m]]
            adj = self._level_adj(l)
            adj[nid, :len(sel)] = sel
            for s in sel:
                self._link(adj, s, nid, width)
            ep = [i for _, i in cand]
        if lvl > self._max_level:
            self._max_level = lvl
            self._ep = nid

    def _link(self, adj: np.ndarray, src: int, dst: int,
              width: int) -> None:
        """Add dst to src's neighbor row, pruning to `width` closest."""
        row = adj[src]
        free = np.nonzero(row < 0)[0]
        if len(free):
            row[free[0]] = dst
            return
        cand = np.concatenate([row, [dst]]).astype(np.int64)
        d = (self.norms[cand] - 2.0 * (self.vecs[cand] @ self.vecs[src])
             + self.norms[src])
        keep = cand[np.argpartition(d, width - 1)[:width]]
        adj[src, :] = keep.astype(np.int32)

    # ---- search ----------------------------------------------------------
    def _dists(self, q: np.ndarray, qn: float, ids: np.ndarray
               ) -> np.ndarray:
        return np.maximum(
            qn + self.norms[ids] - 2.0 * (self.vecs[ids] @ q), 0.0)

    def _greedy_step(self, q: np.ndarray, ep: int, level: int
                     ) -> List[int]:
        """ef=1 greedy descent within one level: walk to the closest
        neighbor until no improvement."""
        qn = float(q @ q)
        adj = self._adj[level] if level < len(self._adj) else None
        if adj is None:
            return [ep]
        cur = ep
        cur_d = float(self._dists(q, qn, np.asarray([cur]))[0])
        while True:
            nb = adj[cur]
            nb = nb[nb >= 0]
            if not len(nb):
                return [cur]
            d = self._dists(q, qn, nb.astype(np.int64))
            j = int(np.argmin(d))
            if d[j] >= cur_d:
                return [cur]
            cur, cur_d = int(nb[j]), float(d[j])

    def _search_layer(self, q: np.ndarray, eps: List[int], ef: int,
                      level: int) -> List[Tuple[float, int]]:
        """Best-first beam bounded by ef; returns [(dist, id)] sorted
        ascending.  Distance evaluations batch per expansion (one
        gather + GEMV over the node's whole neighbor row)."""
        qn = float(q @ q)
        adj = self._adj[level] if level < len(self._adj) else None
        visited = np.zeros(self._n, bool)
        eps = [e for e in eps if 0 <= e < self._n]
        visited[eps] = True
        d0 = self._dists(q, qn, np.asarray(eps, np.int64))
        cand = [(float(d), e) for d, e in zip(d0, eps)]   # min-heap
        heapq.heapify(cand)
        best = [(-float(d), e) for d, e in zip(d0, eps)]  # max-heap
        heapq.heapify(best)
        while len(best) > ef:
            heapq.heappop(best)
        while cand:
            d, c = heapq.heappop(cand)
            if best and d > -best[0][0] and len(best) >= ef:
                break
            if adj is None:
                break
            nb = adj[c]
            nb = nb[(nb >= 0)]
            nb = nb[~visited[nb]]
            if not len(nb):
                continue
            visited[nb] = True
            dn = self._dists(q, qn, nb.astype(np.int64))
            worst = -best[0][0] if best else np.inf
            for dd, ii in zip(dn, nb):
                dd = float(dd)
                if len(best) < ef or dd < worst:
                    heapq.heappush(cand, (dd, int(ii)))
                    heapq.heappush(best, (-dd, int(ii)))
                    if len(best) > ef:
                        heapq.heappop(best)
                    worst = -best[0][0]
        return sorted((-nd, i) for nd, i in best)

    def search(self, queries: np.ndarray, k: int = 10,
               ef_search: Optional[int] = None, **_ignored
               ) -> Tuple[np.ndarray, np.ndarray]:
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        nq = len(q)
        D = np.full((nq, k), np.inf, np.float32)
        I = np.full((nq, k), -1, np.int64)
        if self._n == 0:
            return D, I
        ef = max(k, ef_search or self.ef_search)
        for qi in range(nq):
            ep = [self._ep]
            for l in range(self._max_level, 0, -1):
                ep = self._greedy_step(q[qi], ep[0], l)
            out = self._search_layer(q[qi], ep, ef, 0)[:k]
            for j, (d, i) in enumerate(out):
                D[qi, j] = d
                I[qi, j] = i
        return D, I

    # ---- size ------------------------------------------------------------
    @property
    def size(self) -> int:
        return self._n

    @property
    def dim(self) -> int:
        return self._dim

    def vectors_in_id_order(self) -> np.ndarray:
        return self.vecs[:self._n]

    def vector_of(self, id_: int) -> np.ndarray:
        return self.vecs[id_]

    # ---- persistence -----------------------------------------------------
    def _state_arrays(self) -> Dict[str, np.ndarray]:
        n = self._n
        out = {"vecs": self.vecs[:n], "levels": self.levels[:n]}
        for l, adj in enumerate(self._adj):
            out[f"adj{l}"] = adj[:n]
        return out

    def _state_meta(self) -> dict:
        return {"options": {k: v for k, v in self.options.items()},
                "m": self.m, "ef_construction": self.ef_construction,
                "ef_search": self.ef_search, "ep": self._ep,
                "max_level": self._max_level, "n": self._n,
                "dim": self._dim}

    @classmethod
    def _from_state(cls, arrays: Dict[str, np.ndarray],
                    meta: dict) -> "HnswIndex":
        idx = cls(meta["dim"], m=meta["m"],
                  ef_construction=meta["ef_construction"],
                  ef_search=meta["ef_search"],
                  options=meta.get("options"))
        n = int(meta["n"])
        idx._grow(max(n, 1))
        idx.vecs[:n] = arrays["vecs"]
        idx.norms[:n] = np.einsum("nd,nd->n", arrays["vecs"],
                                  arrays["vecs"])
        idx.levels[:n] = arrays["levels"]
        nlevels = 1 + max((int(k[3:]) for k in arrays
                           if k.startswith("adj")), default=0)
        idx._adj = []
        for l in range(nlevels):
            width = idx.m0 if l == 0 else idx.m
            adj = np.full((len(idx.vecs), width), -1, np.int32)
            a = arrays.get(f"adj{l}")
            if a is not None and len(a):
                adj[:len(a), :a.shape[1]] = a
            idx._adj.append(adj)
        idx._n = n
        idx._ep = int(meta["ep"])
        idx._max_level = int(meta["max_level"])
        return idx
