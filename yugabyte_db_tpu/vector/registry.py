"""ANN index registry: one common interface, pluggable methods.

The analog of the reference's ANNMethodKind dispatch
(src/yb/ann_methods/ann_methods.h registers usearch/hnswlib behind one
factory); ours registers python classes keyed by the DDL method name
(``USING ivfflat``, ``USING hnsw``).  Every engine implements the same
five verbs — build / add / search / save / load — so the tablet, the
executor and the tools never special-case a method.
"""
from __future__ import annotations

import abc
import json
import os
from typing import Dict, Optional, Tuple, Type

import numpy as np

_REGISTRY: Dict[str, Type["AnnIndex"]] = {}


def register_index(name: str, *aliases: str):
    """Class decorator: register an AnnIndex under its DDL method name."""
    def deco(cls):
        cls.method = name
        for n in (name,) + aliases:
            _REGISTRY[n] = cls
        return cls
    return deco


def get_index_cls(method: str) -> Type["AnnIndex"]:
    try:
        return _REGISTRY[method]
    except KeyError:
        raise ValueError(
            f"unknown vector index method {method!r} "
            f"(available: {sorted(set(_REGISTRY))})") from None


def available_methods():
    return sorted({c.method for c in _REGISTRY.values()})


class AnnIndex(abc.ABC):
    """Common ANN index contract.

    Row identity is positional: vector ``i`` of the build matrix (and
    each subsequently added vector, in add order) owns id ``i``; the
    caller keeps the id -> primary-key mapping (the tablet's ``pks``
    list).  ``search`` returns (distances [Q, k], ids [Q, k]) with
    squared-L2 distances; unfilled slots carry ``inf`` / id ``-1``.
    """

    method: str = "?"

    # ---- construction ----------------------------------------------------
    @classmethod
    @abc.abstractmethod
    def build(cls, data: np.ndarray, **options) -> "AnnIndex":
        """Build from an [N, D] float32 matrix."""

    @abc.abstractmethod
    def add(self, vectors: np.ndarray) -> None:
        """Append vectors; they get the next positional ids."""

    # ---- search ----------------------------------------------------------
    @abc.abstractmethod
    def search(self, queries: np.ndarray, k: int = 10, **params
               ) -> Tuple[np.ndarray, np.ndarray]:
        """(distances [Q, k] float32, ids [Q, k] int64)."""

    # ---- size ------------------------------------------------------------
    @property
    @abc.abstractmethod
    def size(self) -> int:
        """Number of indexed vectors (== next positional id)."""

    @property
    @abc.abstractmethod
    def dim(self) -> int:
        """Vector dimensionality."""

    @abc.abstractmethod
    def vectors_in_id_order(self) -> np.ndarray:
        """[size, dim] float32 matrix with row i = the vector owning
        positional id i (the tablet's bootstrap scan-diff compares
        this against a fresh store scan)."""

    def vector_of(self, id_: int) -> np.ndarray:
        """Single indexed vector by positional id (O(1) view where the
        layout allows; the WAL-replay idempotence check in the tablet's
        index maintenance reads one row per re-applied write)."""
        return self.vectors_in_id_order()[id_]

    # ---- persistence -----------------------------------------------------
    @abc.abstractmethod
    def _state_arrays(self) -> Dict[str, np.ndarray]:
        """Index payload as plain numpy arrays (savez fodder)."""

    @abc.abstractmethod
    def _state_meta(self) -> dict:
        """JSON-safe scalar state (knobs, counters)."""

    @classmethod
    @abc.abstractmethod
    def _from_state(cls, arrays: Dict[str, np.ndarray],
                    meta: dict) -> "AnnIndex":
        """Rebuild from _state_arrays + _state_meta output."""

    def save(self, path: str) -> None:
        """Persist to ``path`` (a directory): index.npz + meta.json,
        written atomically (tmp + rename) so a crash mid-save leaves
        either the old index or the new one, never a torn file."""
        os.makedirs(path, exist_ok=True)
        tmp_npz = os.path.join(path, ".index.npz.tmp")
        with open(tmp_npz, "wb") as f:
            np.savez(f, **self._state_arrays())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_npz, os.path.join(path, "index.npz"))
        tmp_meta = os.path.join(path, ".meta.json.tmp")
        with open(tmp_meta, "w") as f:
            json.dump({"method": self.method, **self._state_meta()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_meta, os.path.join(path, "meta.json"))

    @classmethod
    def load(cls, path: str) -> "AnnIndex":
        """Load an index saved by :meth:`save`.  Called on the base
        class, dispatches to the method recorded in meta.json."""
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        target = get_index_cls(meta["method"])
        if cls is not AnnIndex and not issubclass(target, cls):
            raise ValueError(
                f"index at {path} is {meta['method']!r}, not "
                f"{cls.method!r}")
        with np.load(os.path.join(path, "index.npz")) as z:
            arrays = {k: z[k] for k in z.files}
        return target._from_state(arrays, meta)


def merge_topk(dd: np.ndarray, ii: np.ndarray, k: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Final k-merge over candidate (distances [Q, C], ids [Q, C])
    pairs: invalid slots (id < 0) mask to inf, partial-select then
    stable sort, pad to k with inf/-1.  The ONE implementation behind
    the CPU re-rank merge, the add()-tail merge and the sharded-shard
    merge — keep tie-breaking/masking rules here only."""
    dd = np.where(ii >= 0, dd, np.inf).astype(np.float32, copy=False)
    nq = dd.shape[0]
    kk = min(k, dd.shape[1])
    if kk > 0:
        sel = np.argpartition(dd, kk - 1, axis=1)[:, :kk]
        dd = np.take_along_axis(dd, sel, axis=1)
        ii = np.take_along_axis(ii, sel, axis=1)
        o = np.argsort(dd, axis=1, kind="stable")
        dd = np.take_along_axis(dd, o, axis=1)
        ii = np.take_along_axis(ii, o, axis=1)
    D = np.full((nq, k), np.inf, np.float32)
    I = np.full((nq, k), -1, np.int64)
    D[:, :kk] = dd
    I[:, :kk] = np.where(np.isfinite(dd), ii, -1)
    return D, I


def load_index(path: str) -> Optional[AnnIndex]:
    """Best-effort load: None when absent or unreadable (a torn or
    stale on-disk index must degrade to a rebuild, never fail the
    tablet bootstrap)."""
    try:
        return AnnIndex.load(path)
    except (OSError, ValueError, KeyError, json.JSONDecodeError):
        return None
