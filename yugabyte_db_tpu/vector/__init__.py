"""Vector-index subsystem: a pluggable ANN registry with two engines.

The executor's ``CREATE INDEX ... USING ivfflat|hnsw`` DDL resolves an
index method through :mod:`registry`; tablets hold the built
:class:`AnnIndex` objects and persist them alongside tablet data
(reference: src/yb/vector_index/vector_lsm.cc and the usearch/hnswlib
wrappers in src/yb/ann_methods/ — ours swaps the backends for a
TPU-shaped two-stage IVF and a numpy-native HNSW twin).

  * ``ivf``  — two-stage device-friendly IVF: multi-probe candidate
    generation over centroid distances into a wide top-C pool, then an
    exact full-precision GEMM re-rank (one extra GEMM, the MXU-shaped
    hot path), with shape-stable pow2 candidate buckets and
    compile-count accounting mirroring ops/compaction.py.
  * ``hnsw`` — graph index for the host path: numpy adjacency arrays,
    greedy layered descent, ``ef_search`` knob — the recall-frontier
    twin of the IVF engine.
"""
from .registry import (  # noqa: F401
    AnnIndex, available_methods, get_index_cls, register_index,
)
from .ivf import TwoStageIvfIndex  # noqa: F401
from .hnsw import HnswIndex  # noqa: F401
