"""Two-stage IVF: multi-probe candidate generation + exact GEMM re-rank.

The device-friendly ANN engine (reference analog: the IVF half of
pgvector's ivfflat, re-shaped for the MXU).  Search is two GEMM-shaped
stages:

  stage 1 — candidate generation: one [Q, D] x [D, K] centroid-distance
  matmul, per-query top-``nprobe`` lists (multi-probe), gather of the
  probed lists' rows into a wide candidate pool;

  stage 2 — re-rank: ONE exact full-precision GEMM over the pool plus a
  top-k.  On accelerators stage 1 scores the gathered pool in the
  matmul dtype (bf16) and keeps only the top-``rerank_c`` candidates,
  so the exact f32 stage touches a narrow pow2 bucket; on CPU both
  stages are f32 and stage 2 runs list-major as a blocked shared GEMM
  over the BATCH's probed-list union (the union is naturally small —
  centroid ranking is strongly correlated across queries — and a
  shared scan of it beats per-query masked GEMMs by ~2x measured).

All jitted entry points take pow2-bucketed shapes (queries, list
rectangle, candidate pool), so the kernels compile once per bucket —
``kernel_cache_stats()`` mirrors ops/compaction.py's accounting.
"""
from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import numpy as np

from ..ops.vector import kmeans, l2_distance2
from .registry import AnnIndex, merge_topk, register_index

#: process-lifetime kernel-compile accounting (same contract as
#: ops/compaction.py KERNEL_STATS): a signature is one static-shape
#: tuple; jax.jit compiles exactly once per signature, so "compiles"
#: counts cache misses and repeat searches of the same bucket report
#: zero new compiles.
_KERNEL_SIGS: set = set()
KERNEL_STATS = {"compiles": 0, "calls": 0, "cache_hits": 0}


def kernel_cache_stats() -> dict:
    return dict(KERNEL_STATS)


def reset_kernel_stats() -> None:
    KERNEL_STATS.update(compiles=0, calls=0, cache_hits=0)


def _note_kernel_call(sig: tuple) -> None:
    KERNEL_STATS["calls"] += 1
    if sig in _KERNEL_SIGS:
        KERNEL_STATS["cache_hits"] += 1
    else:
        _KERNEL_SIGS.add(sig)
        KERNEL_STATS["compiles"] += 1


def _pow2(n: int, floor: int = 1) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


def _two_stage_device_search(queries, centroids, lists, list_lens,
                             vec_flat, norms_flat, k: int, nprobe: int,
                             rerank_c: int):
    """Jit wrapper with compile accounting.  Every array is a traced
    operand — never close over the dataset (a static self would bake
    multi-GB arrays into the executable as XLA constants)."""
    sig = ("two_stage", queries.shape, centroids.shape[0],
           lists.shape[1], k, nprobe, rerank_c)
    _note_kernel_call(sig)
    return _two_stage_kernel(queries, centroids, lists, list_lens,
                             vec_flat, norms_flat, k=k, nprobe=nprobe,
                             rerank_c=rerank_c)


def _lazy_jit():
    """Import jax lazily so pure-CPU hosts importing the package for
    the numpy path don't pay backend init."""
    global _two_stage_kernel
    import jax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnames=("k", "nprobe", "rerank_c"))
    def _two_stage_kernel(queries, centroids, lists, list_lens,
                          vec_flat, norms_flat, k: int, nprobe: int,
                          rerank_c: int):
        # ---- stage 1: multi-probe candidate generation ----
        dc = l2_distance2(queries, centroids)             # [Q, K]
        _, probe = jax.lax.top_k(-dc, nprobe)             # [Q, nprobe]
        cand = lists[probe]                               # [Q, np, M]
        q_, p_, m_ = cand.shape
        cand = cand.reshape(q_, p_ * m_)                  # [Q, C0]
        valid = (jnp.arange(m_)[None, None, :]
                 < list_lens[probe][:, :, None]).reshape(q_, p_ * m_)
        # coarse scores in the matmul dtype (bf16 on accelerators):
        # cheap wide pass that only has to RANK well enough for the
        # top-C pool to contain the true top-k
        vecs = vec_flat[cand]                             # [Q, C0, D]
        dots = jnp.einsum("qd,qcd->qc",
                          queries.astype(vec_flat.dtype), vecs,
                          preferred_element_type=jnp.float32)
        qn = jnp.sum(queries.astype(jnp.float32) ** 2, axis=1,
                     keepdims=True)
        d1 = qn + norms_flat[cand] - 2.0 * dots
        d1 = jnp.where(valid, d1, jnp.inf)
        c_ = min(rerank_c, p_ * m_)
        _, sel = jax.lax.top_k(-d1, c_)                   # [Q, C]
        pool = jnp.take_along_axis(cand, sel, axis=1)
        pool_valid = jnp.take_along_axis(valid, sel, axis=1)
        # ---- stage 2: exact full-precision GEMM re-rank ----
        pv = vec_flat[pool].astype(jnp.float32)           # [Q, C, D]
        dots2 = jnp.einsum("qd,qcd->qc",
                           queries.astype(jnp.float32), pv,
                           preferred_element_type=jnp.float32)
        d2 = qn + norms_flat[pool] - 2.0 * dots2
        d2 = jnp.where(pool_valid, jnp.maximum(d2, 0.0), jnp.inf)
        neg, pos = jax.lax.top_k(-d2, k)
        ids = jnp.take_along_axis(pool, pos, axis=1)
        ids = jnp.where(jnp.isfinite(-neg), ids, -1)
        return -neg, ids
    return _two_stage_kernel


_two_stage_kernel = None


@register_index("ivfflat", "ivf")
class TwoStageIvfIndex(AnnIndex):
    """IVF with two-stage search behind the AnnIndex contract.

    Layout is list-major: vectors sorted by IVF list so each list is a
    contiguous slice (``starts``/``counts``), with the positional id of
    every sorted row in ``ids``.  The same layout serves both backends:
    the CPU path scans contiguous probed slices with blocked BLAS
    GEMMs; the device path reads it through flat gathers with the list
    rectangle padded to a pow2 width so rebuilds keep the compiled
    kernel signature.

    ``add`` appends to an exact-searched tail (the index's own delta);
    folding the tail back into the lists is a rebuild — the tablet's
    vector-LSM maintenance owns when that happens.
    """

    #: rows per CPU re-rank block: big enough for near-peak BLAS on the
    #: [Q, D] x [D, block] shape, small enough that the [Q, block]
    #: distance tile stays cache-resident for the row-contiguous
    #: top-k partition (measured 1M x 768 / Q=64: 8-32K rows all ~77
    #: qps where 128K drops to ~40)
    CPU_BLOCK = 1 << 14

    def __init__(self, centroids: np.ndarray, sorted_vecs: np.ndarray,
                 ids: np.ndarray, starts: np.ndarray, counts: np.ndarray,
                 options: Optional[dict] = None):
        self.cent = np.ascontiguousarray(centroids, dtype=np.float32)
        self.cent_norms = np.einsum("kd,kd->k", self.cent, self.cent)
        self.sorted = np.ascontiguousarray(sorted_vecs, dtype=np.float32)
        self.sorted_norms = np.einsum("nd,nd->n", self.sorted,
                                      self.sorted)
        self.ids = np.asarray(ids, np.int64)
        self.starts = np.asarray(starts, np.int64)
        self.counts = np.asarray(counts, np.int64)
        self.options = dict(options or {})
        self._tail_vecs: list = []        # added after build (add())
        self._tail_ids: list = []
        self._next_id = (int(self.ids.max()) + 1) if len(self.ids) else 0
        self._device = None               # lazy jnp twin for the kernel
        #: instrumentation: candidate-pool rows of the LAST search
        #: (CPU: probed-union row count; device: the rerank_c bucket) —
        #: the bench records it next to nprobe so qps/recall claims
        #: carry their work parameters
        self.last_pool_rows = 0

    # ---- construction ----------------------------------------------------
    @classmethod
    def build(cls, data: np.ndarray, nlists: int = 100, iters: int = 10,
              sample: int = 100_000, seed: int = 0,
              **extra) -> "TwoStageIvfIndex":
        data = np.asarray(data, np.float32)
        n = len(data)
        nlists = max(1, min(nlists, max(1, n // 2 or 1)))
        if n == 0:
            d = data.shape[1] if data.ndim == 2 else 1
            z = np.zeros((0,), np.int64)
            return cls(np.zeros((1, d), np.float32),
                       np.zeros((0, d), np.float32), z,
                       np.zeros(1, np.int64), np.zeros(1, np.int64),
                       {"nlists": 1, "iters": iters, "seed": seed})
        rng = np.random.default_rng(seed)
        samp = (data if n <= sample
                else data[rng.choice(n, sample, replace=False)])
        cent = kmeans(samp, nlists, iters, seed)
        assign = cls._assign(data, cent)
        order = np.argsort(assign, kind="stable")
        counts = np.bincount(assign, minlength=nlists).astype(np.int64)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
        return cls(cent, data[order], order.astype(np.int64), starts,
                   counts, {"nlists": nlists, "iters": iters,
                            "seed": seed, **extra})

    @staticmethod
    def _assign(data: np.ndarray, cent: np.ndarray) -> np.ndarray:
        """Nearest-centroid assignment, chunked so peak memory stays
        bounded (device kernel when one is attached, BLAS otherwise)."""
        import jax.numpy as jnp
        n = len(data)
        assign = np.empty(n, np.int32)
        step = 1 << 18
        centd = jnp.asarray(cent, jnp.float32)
        for i in range(0, n, step):
            d = l2_distance2(jnp.asarray(data[i:i + step], jnp.float32),
                             centd)
            assign[i:i + step] = np.asarray(jnp.argmin(d, axis=1))
        return assign

    def add(self, vectors: np.ndarray) -> None:
        vectors = np.asarray(vectors, np.float32)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        for v in vectors:
            self._tail_vecs.append(v)
            self._tail_ids.append(self._next_id)
            self._next_id += 1

    # ---- size ------------------------------------------------------------
    @property
    def size(self) -> int:
        return len(self.ids) + len(self._tail_ids)

    @property
    def dim(self) -> int:
        return self.sorted.shape[1] if self.sorted.ndim == 2 else 1

    @property
    def nlists(self) -> int:
        return len(self.counts)

    # ---- search ----------------------------------------------------------
    def default_nprobe(self) -> int:
        """Recall-biased default: a quarter of the lists (isotropic
        data is IVF's worst case; see the bench's rationale)."""
        return max(1, self.nlists // 4)

    def search(self, queries: np.ndarray, k: int = 10,
               nprobe: Optional[int] = None,
               rerank_c: Optional[int] = None,
               backend: Optional[str] = None, **_ignored
               ) -> Tuple[np.ndarray, np.ndarray]:
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        nprobe = min(nprobe or self.default_nprobe(), self.nlists)
        nprobe = max(1, nprobe)
        if backend is None:
            import jax
            backend = ("device" if jax.default_backend() != "cpu"
                       else "cpu")
        if len(self.ids) == 0:
            D = np.full((len(q), k), np.inf, np.float32)
            I = np.full((len(q), k), -1, np.int64)
        elif backend == "device":
            D, I = self._device_search(q, k, nprobe, rerank_c)
        else:
            D, I = self._cpu_search(q, k, nprobe)
        if self._tail_ids:
            D, I = self._merge_tail(q, k, D, I)
        return D, I

    # ---- CPU twin: blocked shared GEMM over the probed-list union -------
    def _cpu_search(self, q: np.ndarray, k: int, nprobe: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
        """Stage 1 picks per-query probe lists; stage 2 re-ranks the
        batch's probed-list UNION with blocked [block, D] x [D, Q]
        GEMMs + per-block partial top-k.  Sharing the union across the
        batch wastes no work in practice (probe sets overlap heavily —
        centroid ranking is dominated by a global list component on
        real and isotropic data alike) and keeps every GEMM at a
        BLAS-friendly shape; each query's candidate set is a superset
        of its own probed lists, so recall can only improve over the
        per-query gather."""
        nq = len(q)
        cd = (np.einsum("qd,qd->q", q, q)[:, None] + self.cent_norms[None]
              - 2.0 * q @ self.cent.T)                     # [Q, K]
        if nprobe < self.nlists:
            probe = np.argpartition(cd, nprobe - 1, axis=1)[:, :nprobe]
            union = np.unique(probe)
        else:
            union = np.arange(self.nlists)
        union = union[self.counts[union] > 0]
        if len(union) == 0:
            self.last_pool_rows = 0
            return (np.full((nq, k), np.inf, np.float32),
                    np.full((nq, k), -1, np.int64))
        # contiguous row segments of the union (lists are list-major
        # slices; adjacent probed lists coalesce into one segment).
        # Segment i spans union positions heads[i] .. heads[i+1]-1, so
        # its row range ends at the LAST coalesced list's end — never
        # at the next segment's start (that would sweep every
        # unprobed list sitting between two probed runs into the scan).
        # Gap-tolerant: two probed runs separated by fewer than
        # GAP_ROWS unprobed rows merge anyway — scanning the small gap
        # (its rows become extra exact-ranked candidates; recall can
        # only improve) is cheaper than fragmenting the blocked GEMM
        # into sub-block segments (measured ~15% at 1M x 768 with ~400
        # scattered probed lists).  last_pool_rows reports the rows
        # actually scanned, gaps included.
        seg_start = self.starts[union]
        seg_end = seg_start + self.counts[union]
        gap = self.CPU_BLOCK // 4
        keep = np.ones(len(union), bool)
        keep[1:] = seg_start[1:] > seg_end[:-1] + gap
        heads = np.nonzero(keep)[0]
        seg_lo = seg_start[heads]
        seg_hi = np.concatenate([seg_end[heads[1:] - 1], seg_end[-1:]])
        self.last_pool_rows = int((seg_hi - seg_lo).sum())
        # re-split long segments into GEMM blocks.  Query-major
        # orientation throughout: dots [Q, block] keeps each query's
        # distance row contiguous, so both the BLAS epilogue and the
        # per-block argpartition stream cache lines instead of striding
        # (measured ~1.7x over the block-major orientation at 1M x 768)
        qn = np.einsum("qd,qd->q", q, q)
        win_d: list = []
        win_i: list = []
        for lo, hi in zip(seg_lo, seg_hi):
            lo, hi = int(lo), int(hi)
            for b0 in range(lo, hi, self.CPU_BLOCK):
                b1 = min(b0 + self.CPU_BLOCK, hi)
                dots = q @ self.sorted[b0:b1].T             # [Q, b]
                dist = (qn[:, None] - 2.0 * dots
                        + self.sorted_norms[None, b0:b1])
                kk = min(k, b1 - b0)
                sel = np.argpartition(dist, kk - 1, axis=1)[:, :kk]
                win_d.append(np.take_along_axis(dist, sel, axis=1))
                win_i.append(self.ids[b0 + sel])
        D, I = merge_topk(np.concatenate(win_d, axis=1),
                          np.concatenate(win_i, axis=1), k)
        return np.maximum(D, 0.0), I

    # ---- device path: jitted two-stage kernel ---------------------------
    def _device_arrays(self):
        """Lazy device twin: flat vectors in the matmul dtype, f32
        norms, and the list rectangle padded to a pow2 width (stable
        kernel signature across rebuilds of similar size)."""
        if self._device is None:
            import jax.numpy as jnp
            from ..ops.vector import _mm_dtype
            m = _pow2(max(1, int(self.counts.max()) if len(self.counts)
                          else 1), floor=8)
            lists = np.zeros((self.nlists, m), np.int32)
            for li in range(self.nlists):
                s, c = int(self.starts[li]), int(self.counts[li])
                lists[li, :c] = np.arange(s, s + c)
            self._device = {
                "cent": jnp.asarray(self.cent, jnp.float32),
                "lists": jnp.asarray(lists),
                "lens": jnp.asarray(self.counts.astype(np.int32)),
                "vecs": jnp.asarray(self.sorted).astype(_mm_dtype()),
                "norms": jnp.asarray(self.sorted_norms, jnp.float32),
            }
        return self._device

    def _device_search(self, q: np.ndarray, k: int, nprobe: int,
                       rerank_c: Optional[int]
                       ) -> Tuple[np.ndarray, np.ndarray]:
        global _two_stage_kernel
        import jax.numpy as jnp
        if _two_stage_kernel is None:
            _lazy_jit()
        dv = self._device_arrays()
        n = len(self.ids)
        # the kernel's pool is at most nprobe * padded-list-width wide;
        # top_k(k) over a narrower pool would raise, so clamp and pad
        # the missing slots with inf/-1 like every other search path
        m_pad = int(dv["lists"].shape[1])
        k_eff = min(k, n, nprobe * m_pad)
        c = rerank_c or self.options.get("rerank_c") or 4 * k
        c = _pow2(max(min(c, n), k_eff))
        self.last_pool_rows = c
        # pow2 query bucket: searches of 1..Q queries share compiles
        qb = _pow2(len(q))
        qpad = np.zeros((qb, q.shape[1]), np.float32)
        qpad[:len(q)] = q
        d, i = _two_stage_device_search(
            jnp.asarray(qpad), dv["cent"], dv["lists"], dv["lens"],
            dv["vecs"], dv["norms"], k_eff, nprobe, c)
        d = np.asarray(d)[:len(q)]
        i = np.asarray(i, np.int64)[:len(q)]
        # positions -> positional ids; -1 padding stays -1
        i = np.where(i >= 0, self.ids[np.clip(i, 0, max(n - 1, 0))], -1)
        if k_eff < k:
            d = np.pad(d, ((0, 0), (0, k - k_eff)),
                       constant_values=np.inf)
            i = np.pad(i, ((0, 0), (0, k - k_eff)), constant_values=-1)
        return d, i

    # ---- tail (add()-ed vectors): exact merge ---------------------------
    def _merge_tail(self, q: np.ndarray, k: int, D: np.ndarray,
                    I: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        tv = np.stack(self._tail_vecs)
        ti = np.asarray(self._tail_ids, np.int64)
        dist = (np.einsum("qd,qd->q", q, q)[:, None]
                + np.einsum("td,td->t", tv, tv)[None, :]
                - 2.0 * q @ tv.T)
        dist = np.maximum(dist, 0.0)
        return merge_topk(
            np.concatenate([D, dist], axis=1),
            np.concatenate(
                [I, np.broadcast_to(ti, (len(q), len(ti)))], axis=1),
            k)

    def _inv_ids(self) -> np.ndarray:
        """positional id -> sorted-row position (built once, 8 bytes a
        row — not a second copy of the vectors)."""
        if getattr(self, "_inv", None) is None:
            n = len(self.ids)
            self._inv = np.empty(n, np.int64)
            self._inv[self.ids] = np.arange(n)
        return self._inv

    def vectors_in_id_order(self) -> np.ndarray:
        out = self.sorted[self._inv_ids()]
        if self._tail_vecs:
            out = np.concatenate([out, np.stack(self._tail_vecs)])
        return out

    def vector_of(self, id_: int) -> np.ndarray:
        n = len(self.ids)
        if id_ >= n:
            return self._tail_vecs[id_ - n]
        return self.sorted[self._inv_ids()[id_]]

    # ---- persistence -----------------------------------------------------
    def _state_arrays(self) -> Dict[str, np.ndarray]:
        tail_v = (np.stack(self._tail_vecs) if self._tail_vecs
                  else np.zeros((0, self.dim), np.float32))
        return {"cent": self.cent, "sorted": self.sorted,
                "ids": self.ids, "starts": self.starts,
                "counts": self.counts, "tail_vecs": tail_v,
                "tail_ids": np.asarray(self._tail_ids, np.int64)}

    def _state_meta(self) -> dict:
        return {"options": self.options}

    @classmethod
    def _from_state(cls, arrays: Dict[str, np.ndarray],
                    meta: dict) -> "TwoStageIvfIndex":
        idx = cls(arrays["cent"], arrays["sorted"], arrays["ids"],
                  arrays["starts"], arrays["counts"],
                  meta.get("options"))
        if len(arrays.get("tail_ids", ())):
            idx._tail_vecs = list(arrays["tail_vecs"])
            idx._tail_ids = [int(x) for x in arrays["tail_ids"]]
            idx._next_id = max(idx._next_id,
                               max(idx._tail_ids) + 1)
        return idx
