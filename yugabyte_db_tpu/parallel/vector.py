"""Sharded vector search: base vectors split across the mesh, per-shard
top-k, then a gather+re-rank — model-parallel ANN over ICI."""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .mesh import BLOCKS_AXIS, TABLETS_AXIS, TabletMesh, shard_map_compat


def sharded_exact_search(tm: TabletMesh, queries: np.ndarray,
                         base_sharded: jnp.ndarray, k: int
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """base_sharded: [S, N_shard, D] sharded over (tablets, blocks) as
    [T, B, N, D]. Returns global (distances [Q, k], indices [Q, k]) where
    indices are global row ids (shard_offset + local)."""
    T, B = tm.num_tablet_shards, tm.num_block_shards
    n_shard = base_sharded.shape[1]      # [S, N_shard, D] input

    def shard_fn(q, base):
        b = base.reshape(base.shape[-2], base.shape[-1])
        d = (jnp.sum(q ** 2, axis=1, keepdims=True)
             + jnp.sum(b.astype(jnp.float32) ** 2, axis=1)[None, :]
             - 2.0 * jax.lax.dot_general(
                 q.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                 (((1,), (1,)), ((), ())),
                 preferred_element_type=jnp.float32))
        d = jnp.maximum(d, 0.0)   # bf16 rounding can push |q-b|^2 below 0
        neg, idx = jax.lax.top_k(-d, k)
        ti = jax.lax.axis_index(TABLETS_AXIS)
        bi = jax.lax.axis_index(BLOCKS_AXIS)
        shard_id = ti * B + bi
        gidx = idx + shard_id * n_shard
        # gather all shards' candidates
        alld = jax.lax.all_gather(-neg, TABLETS_AXIS)
        alld = jax.lax.all_gather(alld, BLOCKS_AXIS)     # [B, T, Q, k]
        alli = jax.lax.all_gather(gidx, TABLETS_AXIS)
        alli = jax.lax.all_gather(alli, BLOCKS_AXIS)
        Q = q.shape[0]
        alld = jnp.moveaxis(alld.reshape(T * B, Q, k), 0, 1).reshape(Q, -1)
        alli = jnp.moveaxis(alli.reshape(T * B, Q, k), 0, 1).reshape(Q, -1)
        neg2, pos = jax.lax.top_k(-alld, k)
        return -neg2, jnp.take_along_axis(alli, pos, axis=1)

    fn = jax.jit(shard_map_compat(
        shard_fn, mesh=tm.mesh,
        in_specs=(P(), P(TABLETS_AXIS, BLOCKS_AXIS, None, None)),
        out_specs=(P(), P())))
    d, i = fn(jnp.asarray(queries, jnp.float32),
              base_sharded.reshape(T, B, n_shard, -1))
    return np.asarray(d), np.asarray(i)


def sharded_ann_search(queries: np.ndarray, indexes, k: int,
                       **params) -> Tuple[np.ndarray, np.ndarray]:
    """Sharded search across per-shard ANN indexes (any registry
    method — the index-aware twin of sharded_exact_search's all_gather
    merge): per-shard top-k through each AnnIndex, then one host-side
    gather + re-rank with ids offset into the global row space
    (shard s owns ids [sum(sizes[:s]), sum(sizes[:s+1]))).  Shards may
    mix methods (an IVF shard next to an HNSW shard) — the merge only
    sees (distance, global id) pairs."""
    q = np.asarray(queries, np.float32)
    if q.ndim == 1:
        q = q[None, :]
    all_d = []
    all_i = []
    offset = 0
    for idx in indexes:
        d, i = idx.search(q, k=min(k, max(idx.size, 1)), **params)
        gi = np.where(i >= 0, i + offset, -1)
        all_d.append(np.asarray(d, np.float32))
        all_i.append(gi.astype(np.int64))
        offset += idx.size
    if not all_d:
        return (np.full((len(q), k), np.inf, np.float32),
                np.full((len(q), k), -1, np.int64))
    from ..vector.registry import merge_topk
    return merge_topk(np.concatenate(all_d, axis=1),
                      np.concatenate(all_i, axis=1), k)
