"""Device meshes mirroring tablet sharding.

The reference's intra-query parallelism clones one logical scan into
per-tablet requests and combines partial aggregates client-side
(reference: src/yb/yql/pggate/pg_doc_op.h:115-126
PopulateParallelSelectOps). The TPU-native equivalent maps tablet
shards onto a mesh axis ("tablets") so the combine is a `lax.psum`
riding ICI, and adds a second axis ("blocks") for splitting one huge
tablet's key-range across devices (the sequence-parallel analog of the
reference's GetTableKeyRanges chunking, src/yb/tablet/tablet.cc:5698).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: the public `jax.shard_map`
    (with `check_vma`) landed after 0.4.x; older images carry it as
    `jax.experimental.shard_map.shard_map` (with `check_rep`). Every
    shard_map in the engine goes through here so version drift is gated
    in ONE place instead of at each call site."""
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    try:
        return _sm(fn, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=False)
    except TypeError:
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

TABLETS_AXIS = "tablets"
BLOCKS_AXIS = "blocks"


@dataclass(frozen=True)
class TabletMesh:
    mesh: Mesh

    @property
    def num_tablet_shards(self) -> int:
        return self.mesh.shape[TABLETS_AXIS]

    @property
    def num_block_shards(self) -> int:
        return self.mesh.shape.get(BLOCKS_AXIS, 1)

    def tablet_sharding(self, extra_dims: int = 1) -> NamedSharding:
        """[T, ...] arrays sharded over the tablets axis."""
        return NamedSharding(self.mesh,
                             P(TABLETS_AXIS, *([None] * extra_dims)))

    def tablet_block_sharding(self, extra_dims: int = 1) -> NamedSharding:
        """[T, B, ...] arrays sharded over both axes."""
        return NamedSharding(
            self.mesh, P(TABLETS_AXIS, BLOCKS_AXIS, *([None] * extra_dims)))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def tablet_mesh(num_tablet_shards: Optional[int] = None,
                num_block_shards: int = 1,
                devices: Optional[Sequence] = None) -> TabletMesh:
    devices = list(devices if devices is not None else jax.devices())
    if num_tablet_shards is None:
        num_tablet_shards = len(devices) // num_block_shards
    need = num_tablet_shards * num_block_shards
    if need > len(devices):
        raise ValueError(
            f"mesh {num_tablet_shards}x{num_block_shards} needs {need} "
            f"devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(num_tablet_shards,
                                           num_block_shards)
    return TabletMesh(Mesh(arr, (TABLETS_AXIS, BLOCKS_AXIS)))
