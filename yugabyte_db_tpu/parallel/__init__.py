from .mesh import tablet_mesh, TabletMesh  # noqa: F401
from .distributed_scan import DistributedScanKernel, distributed_scan_aggregate  # noqa: F401
from .vector import sharded_ann_search, sharded_exact_search  # noqa: F401
