"""Distributed scan: per-shard kernels + ICI collectives for the combine.

Each device holds its tablet shard's columnar batch; the jitted step runs
the same scan kernel per shard under `shard_map` and combines partial
aggregates with psum/pmin/pmax over the mesh axes — the TPU translation
of pggate's per-tablet fan-out + client-side partial combine (reference:
src/yb/yql/pggate/pg_doc_op.h:117-121, aggregate combination in
src/postgres yb_scan paths).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.device_batch import DeviceBatch, bucket_rows, _pad, f64_conversion
from ..ops.expr import collect_constants, expr_signature
from ..ops.scan import (
    AggSpec, GroupSpec, _build_kernel, _expand_avg, _group_strategy,
    _rescale_outs, _static_scales,
)
from ..storage.columnar import ColumnarBlock
from .mesh import BLOCKS_AXIS, TABLETS_AXIS, TabletMesh, shard_map_compat


@dataclass
class ShardedBatch:
    """[S, N] columnar arrays sharded over the mesh (S = total shards =
    tablets * blocks, N = per-shard padded rows)."""
    n_rows_per_shard: List[int]
    cols: Dict[int, jnp.ndarray]
    nulls: Dict[int, jnp.ndarray]
    # GLOBAL per-column (min, max) across all shards — static SUM scales
    # derived from these are identical on every shard, so int64 partials
    # psum exactly over ICI with no in-kernel pmax round
    col_bounds: Dict[int, Tuple[float, float]]
    valid: jnp.ndarray
    key_hash: jnp.ndarray
    ht: jnp.ndarray
    write_id: jnp.ndarray
    tombstone: jnp.ndarray
    unique_keys: bool
    mesh: TabletMesh

    @property
    def padded_rows(self) -> int:
        # valid is [tablet_shards, block_shards, N] after device_put
        return int(self.valid.shape[-1])

    @property
    def num_shards(self) -> int:
        return int(np.prod(self.valid.shape[:-1]))


def build_sharded_batch(tm: TabletMesh,
                        per_shard_blocks: Sequence[Sequence[ColumnarBlock]],
                        columns: Sequence[int]) -> ShardedBatch:
    """Stack per-shard block lists into mesh-sharded [S, N] arrays. The
    number of shard slots must equal the mesh size; short shards pad."""
    S = tm.num_tablet_shards * tm.num_block_shards
    if len(per_shard_blocks) != S:
        raise ValueError(f"need {S} shard block-lists, got "
                         f"{len(per_shard_blocks)}")
    ns = [sum(b.n for b in blocks) for blocks in per_shard_blocks]
    pad = bucket_rows(max(max(ns), 1))

    def stack(get, dtype=None):
        if dtype is None:
            # take the real dtype from any nonempty shard so empty shards
            # don't promote int columns to float64 via np.stack
            for blocks in per_shard_blocks:
                for b in blocks:
                    dtype = get(b).dtype
                    break
                if dtype is not None:
                    break
        rows = []
        for blocks, n in zip(per_shard_blocks, ns):
            parts = [get(b) for b in blocks]
            arr = (np.concatenate(parts) if parts
                   else np.zeros(0, dtype or np.float32))
            rows.append(_pad(arr, pad))
        return np.stack(rows)

    cols: Dict[int, jnp.ndarray] = {}
    nulls: Dict[int, jnp.ndarray] = {}
    col_bounds: Dict[int, Tuple[float, float]] = {}

    def put(tm, arr):
        T, B = tm.num_tablet_shards, tm.num_block_shards
        arr = arr.reshape(T, B, *arr.shape[1:])
        return jax.device_put(arr, tm.tablet_block_sharding(
            extra_dims=arr.ndim - 2))

    for cid in columns:
        # decide the device dtype GLOBALLY (all shards must agree) with
        # the same policy as the single-device builder: integer-valued
        # f64 columns ship as exact int32; fractional f64 follows the
        # backend policy (f64 on CPU, f32 on TPU — sums stay exact via
        # the kernel's int64 fixed-point accumulation)
        conv = f64_conversion(
            [b.fixed[cid][0] if cid in b.fixed else b.pk[cid]
             for blocks in per_shard_blocks for b in blocks])

        def getv(b, cid=cid, conv=conv):
            v = b.fixed[cid][0] if cid in b.fixed else b.pk[cid]
            return v.astype(conv) if conv is not None else v

        def getn(b, cid=cid):
            if cid in b.fixed:
                return b.fixed[cid][1]
            return np.zeros(b.n, bool)
        stacked = stack(getv)
        if stacked.size and stacked.dtype.kind in "fiu":
            # padding zeros are included — harmless: masked rows
            # contribute 0 to any SUM, the bound only sets the scale
            col_bounds[cid] = (float(stacked.min()), float(stacked.max()))
        cols[cid] = put(tm, stacked)
        nulls[cid] = put(tm, stack(getn, bool))
    valid_rows = []
    for n in ns:
        v = np.zeros(pad, bool)
        v[:n] = True
        valid_rows.append(v)
    return ShardedBatch(
        n_rows_per_shard=ns, cols=cols, nulls=nulls,
        col_bounds=col_bounds,
        valid=put(tm, np.stack(valid_rows)),
        key_hash=put(tm, stack(lambda b: b.key_hash, np.uint64)),
        ht=put(tm, stack(lambda b: b.ht, np.uint64)),
        write_id=put(tm, stack(lambda b: b.write_id, np.uint32)),
        tombstone=put(tm, stack(lambda b: b.tombstone, bool)),
        unique_keys=all(b.unique_keys
                        for blocks in per_shard_blocks for b in blocks),
        mesh=tm)


_COMBINE = {"sum": "psum", "count": "psum", "min": "pmin", "max": "pmax"}


class DistributedScanKernel:
    def __init__(self):
        self._cache: Dict[tuple, object] = {}
        self.compiles = 0

    def _get(self, sig, tm: TabletMesh, where, aggs, group, mvcc_mode,
             static_sums, strategy):
        fn = self._cache.get(sig)
        if fn is not None:
            return fn
        axes = (TABLETS_AXIS, BLOCKS_AXIS)
        S = tm.num_tablet_shards * tm.num_block_shards
        # static SUM scales derive from GLOBAL host-side column bounds,
        # so every shard quantizes identically and the int64 partials
        # psum EXACTLY over ICI with no collective before the sum; SUMs
        # without usable bounds fall back to the dynamic in-kernel scale,
        # where axis_names pmax-combines max|v| across shards first
        local = _build_kernel(where, aggs, group, mvcc_mode,
                              axis_names=axes, row_multiplier=S,
                              static_sums=static_sums, strategy=strategy)

        def shard_fn(cols, nulls, consts, valid, key_hash, ht, wid, tomb,
                     read_ht, sum_scales):
            # local shard view: [1, 1, N] → [N]
            sq = lambda a: a.reshape(a.shape[-1])
            lcols = {k: sq(v) for k, v in cols.items()}
            lnulls = {k: sq(v) for k, v in nulls.items()}
            outs, scales, counts, _ = local(
                lcols, lnulls, consts, sq(valid), sq(key_hash), sq(ht),
                sq(wid), sq(tomb), read_ht, sum_scales)
            combined = []
            for a, o in zip(aggs, outs):
                kind = _COMBINE["count" if a.expr is None else a.op]
                for ax in axes:
                    if kind == "psum":
                        o = jax.lax.psum(o, ax)
                    elif kind == "pmin":
                        o = jax.lax.pmin(o, ax)
                    else:
                        o = jax.lax.pmax(o, ax)
                combined.append(o)
            for ax in axes:
                counts = jax.lax.psum(counts, ax)
            # scales are identical on every shard (pmax'd vmax) and pass
            # through replicated; each float-sum fallback lane is a
            # per-shard partial that psums like the int64 lane
            cscales = []
            for s in scales:
                if isinstance(s, tuple):
                    fb = s[1]
                    for ax in axes:
                        fb = jax.lax.psum(fb, ax)
                    cscales.append((s[0], fb))
                else:
                    cscales.append(s)
            return tuple(combined), tuple(cscales), counts

        spec3 = P(TABLETS_AXIS, BLOCKS_AXIS, None)
        in_specs = (
            {k: spec3 for k in sig_cols(sig)}, {k: spec3 for k in sig_cols(sig)},
            P(), spec3, spec3, spec3, spec3, spec3, P(), P())
        smapped = shard_map_compat(
            shard_fn, mesh=tm.mesh, in_specs=in_specs,
            out_specs=(tuple(P() for _ in aggs), tuple(P() for _ in aggs),
                       P()))
        fn = jax.jit(smapped)
        self._cache[sig] = fn
        self.compiles += 1
        return fn

    def run(self, batch: ShardedBatch,
            where: Optional[tuple] = None,
            aggs: Sequence[AggSpec] = (),
            group: Optional[GroupSpec] = None,
            read_ht: Optional[int] = None):
        aggs = tuple(_expand_avg(aggs))
        if read_ht is None:
            mvcc_mode = "none"
        elif batch.unique_keys:
            mvcc_mode = "visible"
        else:
            mvcc_mode = "dedup"   # per-shard dedup: correct because one doc
            # key lives in exactly one tablet shard and one block shard
        consts: List = []
        if where is not None:
            collect_constants(where, consts)
        for a in aggs:
            if a.expr is not None:
                collect_constants(a.expr, consts)
        col_sig = tuple(sorted(
            (cid, str(v.dtype)) for cid, v in batch.cols.items()))
        tm = batch.mesh
        static_sums, scale_args = _static_scales(
            aggs, batch.col_bounds,
            batch.padded_rows * batch.num_shards, batch.cols)
        strategy = _group_strategy()
        sig = (
            id(tm.mesh), expr_signature(where) if where is not None else None,
            tuple(a.signature() for a in aggs),
            group.cols if group else None, mvcc_mode,
            batch.padded_rows, col_sig, static_sums, strategy,
        )
        fn = self._get(sig, tm, where, aggs, group, mvcc_mode,
                       static_sums, strategy)
        outs, scales, counts = fn(
            batch.cols, batch.nulls,
            [jnp.asarray(c) for c in consts], batch.valid,
            batch.key_hash, batch.ht, batch.write_id, batch.tombstone,
            jnp.uint64(read_ht if read_ht is not None
                       else 0xFFFFFFFFFFFFFFFF),
            scale_args)
        return _rescale_outs(outs, scales), counts


def sig_cols(sig) -> Tuple[int, ...]:
    return tuple(cid for cid, _ in sig[-3])


_DEFAULT = DistributedScanKernel()


def distributed_scan_aggregate(batch: ShardedBatch, where=None, aggs=(),
                               group=None, read_ht=None):
    return _DEFAULT.run(batch, where, aggs, group, read_ht)
