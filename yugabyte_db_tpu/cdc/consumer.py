"""CDC consumption + cross-cluster (xCluster) replication.

Reference: the CDC service streams WAL changes per tablet
(src/yb/cdc/cdc_service.cc, virtual-WAL merging of per-tablet streams
cdc/cdcsdk_virtual_wal.cc); xCluster pulls those changes into another
universe (src/yb/tserver/xcluster_consumer.cc, xcluster_poller.cc,
xcluster_output_client.cc).

CdcStream merges per-tablet change streams for one table (the virtual
WAL), tracking per-tablet checkpoints. XClusterReplicator pumps a
CdcStream into a target cluster's client — async, at-least-once, with
idempotent upserts (same-row re-application converges).
"""
from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple

from ..client import YBClient
from ..docdb.operations import ReadRequest, RowOp
from ..rpc.messenger import RpcError
from ..utils.tasks import cancel_and_drain


class CdcStream:
    def __init__(self, client: YBClient, table: str,
                 stream_id: Optional[str] = None):
        self.client = client
        self.table = table
        self.stream_id = stream_id      # set -> checkpoints persist in the
        self.checkpoints: Dict[str, int] = {}
        self._num_tablets = 0
        # per-tablet replicated-up-to hybrid time (xCluster safe time
        # inputs; reference: xcluster_safe_time_service.cc)
        self._tablet_safe_ht: Dict[str, int] = {}
        # provisional buffers per txn until commit/abort arrives
        self._pending_txns: Dict[str, List[dict]] = {}

    @classmethod
    async def create(cls, client: YBClient, table: str) -> "CdcStream":
        """Registered stream: checkpoints survive consumer restarts in
        the master's catalog (cdc_state_table analog)."""
        r = await client._master_call("create_cdc_stream",
                                      {"table": table})
        return cls(client, table, stream_id=r["stream_id"])

    @classmethod
    async def resume(cls, client: YBClient, stream_id: str) -> "CdcStream":
        r = await client._master_call("get_cdc_stream",
                                      {"stream_id": stream_id})
        st = cls(client, r["table"], stream_id=stream_id)
        st.checkpoints = dict(r.get("checkpoints", {}))
        return st

    async def poll(self, limit_per_tablet: int = 1000) -> List[dict]:
        """One round of the virtual WAL: fetch + merge committed changes
        from every tablet."""
        ct = await self.client._table(self.table, refresh=True)
        self._num_tablets = len(ct.locations)
        live = {loc.tablet_id for loc in ct.locations}
        # tablets split away no longer report; keeping their stale HT
        # would freeze min() forever
        self._tablet_safe_ht = {k: v for k, v in
                                self._tablet_safe_ht.items() if k in live}
        out: List[dict] = []
        for loc in ct.locations:
            payload = {"tablet_id": loc.tablet_id,
                       "from_index": self.checkpoints.get(loc.tablet_id, 0),
                       "limit": limit_per_tablet}
            try:
                resp = await self.client._call_leader(
                    ct, loc.tablet_id, "get_changes", payload)
            except RpcError as e:
                if e.code == "CACHE_MISS_ERROR":
                    # WAL GC trimmed past our checkpoint: unrecoverable
                    # from the log — the consumer must resync (full scan)
                    raise
                continue
            new_cp = resp["checkpoint"]
            for ch in resp["changes"]:
                ch["tablet_id"] = loc.tablet_id
                if ch.get("provisional"):
                    self._pending_txns.setdefault(
                        ch["txn_id"], []).append(ch)
                elif ch["op"] == "commit":
                    for p in self._pending_txns.pop(ch["txn_id"], []):
                        out.append({"op": p["op"], "row": p["row"],
                                    "ht": ch["ht"],
                                    "txn_id": ch["txn_id"]})
                elif ch["op"] == "abort":
                    self._pending_txns.pop(ch["txn_id"], None)
                elif ch["op"] == "abort_sub":
                    # ROLLBACK TO SAVEPOINT: discard this tablet's
                    # buffered provisional records of the rolled-back
                    # subtransactions (per-tablet log order makes the
                    # sub >= from_sub filter exact)
                    chs = self._pending_txns.get(ch["txn_id"])
                    if chs:
                        self._pending_txns[ch["txn_id"]] = [
                            p for p in chs
                            if not (p.get("tablet_id") == loc.tablet_id
                                    and p.get("sub", 0)
                                    >= ch["from_sub"])]
                else:
                    out.append(ch)
            # hold the checkpoint back to before the OLDEST still-pending
            # provisional change from this tablet, so a restarted consumer
            # re-reads it (re-buffering provisional records is idempotent)
            pending_min = min(
                (p["index"] for chs in self._pending_txns.values()
                 for p in chs if p.get("tablet_id") == loc.tablet_id),
                default=None)
            if pending_min is not None:
                new_cp = min(new_cp, pending_min - 1)
            self.checkpoints[loc.tablet_id] = max(
                self.checkpoints.get(loc.tablet_id, 0), new_cp)
            # safe time only advances while no provisional txn from this
            # tablet is still buffered (its commit HT is unknown yet)
            if pending_min is None and "safe_ht" in resp:
                self._tablet_safe_ht[loc.tablet_id] = max(
                    self._tablet_safe_ht.get(loc.tablet_id, 0),
                    resp["safe_ht"])
        out.sort(key=lambda c: c.get("ht", 0))
        return out

    def safe_time(self) -> int:
        """Min replicated-up-to HT across tablets: a reader using this
        as read_ht sees a consistent, fully-replicated cut. 0 until
        every tablet has reported."""
        live = set(self._tablet_safe_ht)
        if not self._num_tablets or len(live) < self._num_tablets:
            return 0
        return min(self._tablet_safe_ht.values())

    async def commit_checkpoints(self) -> None:
        """Persist checkpoints AFTER the consumer has durably handled the
        delivered changes (at-least-once: call this once the batch is
        applied downstream)."""
        if self.stream_id is None:
            return
        for tablet_id, idx in self.checkpoints.items():
            try:
                await self.client._master_call(
                    "set_cdc_checkpoint",
                    {"stream_id": self.stream_id,
                     "tablet_id": tablet_id, "index": idx})
            except RpcError:
                pass


class XClusterReplicator:
    """Async table replication between two universes (producer pull)."""

    def __init__(self, source: YBClient, target: YBClient, table: str,
                 poll_interval: float = 0.1):
        self.stream = CdcStream(source, table)
        self.target = target
        self.table = table
        self.poll_interval = poll_interval
        self._task: Optional[asyncio.Task] = None
        self._running = False
        self.replicated = 0
        # source schema version already mirrored onto the target (DDL
        # replication, reference: xCluster automatic-mode DDL queue —
        # master/xcluster/xcluster_ddl_queue_handler.cc; ours
        # reconciles the target schema whenever the source version
        # moves, BEFORE applying that round's row images, because the
        # row path silently drops columns the target doesn't know)
        self._applied_schema_version: Optional[int] = None

    async def ensure_target_table(self):
        names = {t["name"] for t in await self.target.list_tables()}
        if self.table not in names:
            ct = await self.stream.client._table(self.table)
            await self.target.create_table(ct.info, num_tablets=len(
                ct.locations))

    async def step(self) -> int:
        # poll() advances in-memory checkpoints optimistically; if the
        # target rejects the batch, roll them (and the safe-ht inputs)
        # back so the next step re-reads the same changes instead of
        # silently dropping them under an advancing safe time
        cps = dict(self.stream.checkpoints)
        shts = dict(self.stream._tablet_safe_ht)
        try:
            return await self._step_inner()
        except Exception as e:
            self.stream.checkpoints = cps
            self.stream._tablet_safe_ht = shts
            if isinstance(e, RpcError) and e.code == "CACHE_MISS_ERROR":
                # WAL GC outran the stream (or setup started on a table
                # with trimmed history): full resync, then stream from
                # the recorded tails
                return await self.resync()
            raise

    async def resync(self) -> int:
        """Bootstrap/recovery copy (reference: xCluster bootstrap via
        snapshot + stream-from-checkpoint). Ordering that makes it
        correct:
        1. record each source tablet's log tail (held below any live
           txn's first intent so its commit can replay);
        2. pick a source snapshot HT R and copy rows AT R, writing
           them at external_ht=R — changes after R stream with
           ht > R and therefore win over the copy in target MVCC;
        3. deletes reconcile: target rows absent from the source
           snapshot are deleted at R (the WAL holding their delete
           may be GC'd).
        Changes between tail-record and the scan replay from the
        stream and re-apply idempotently above R."""
        src = self.stream.client
        ct = await src._table(self.table, refresh=True)
        tails = {}
        snapshot_ht = 0
        for loc in ct.locations:
            r = await src._call_leader(
                ct, loc.tablet_id, "get_changes",
                {"tablet_id": loc.tablet_id, "from_index": -1})
            tails[loc.tablet_id] = r["checkpoint"]
            snapshot_ht = max(snapshot_ht, r.get("safe_ht") or 0)
        pk_names = [c.name for c in ct.info.schema.key_columns]
        n = 0
        src_pks = set()
        async for page in src.scan_pages(
                self.table, ReadRequest("", read_ht=snapshot_ht or None),
                page_size=2000):
            for r in page:
                src_pks.add(tuple(r[k] for k in pk_names))
            await self.target.write(
                self.table, [RowOp("upsert", r) for r in page],
                external_ht=snapshot_ht or None)
            n += len(page)
        # reconcile deletes that happened during the unstreamable gap
        stale = []
        async for page in self.target.scan_pages(
                self.table, ReadRequest("", columns=tuple(pk_names)),
                page_size=2000):
            for r in page:
                if tuple(r[k] for k in pk_names) not in src_pks:
                    stale.append({k: r[k] for k in pk_names})
        if stale:
            await self.target.write(
                self.table, [RowOp("delete", r) for r in stale],
                external_ht=snapshot_ht or None)
        self.stream.checkpoints = dict(tails)
        self.stream._pending_txns.clear()
        await self.stream.commit_checkpoints()
        self.replicated += n
        return n

    async def _maybe_replicate_ddl(self, changes) -> None:
        """Mirror source schema changes (ADD/DROP COLUMN) onto the
        target BEFORE the round's row images apply. Normally a version
        compare against the cache poll() just refreshed; when the round
        actually carries changes the schema is re-fetched — an ALTER
        landing between poll's refresh and get_changes would otherwise
        leave this round's new-column values silently dropped by the
        target's row path."""
        src_ct = await self.stream.client._table(self.table,
                                                 refresh=bool(changes))
        ver = src_ct.info.schema.version
        if ver == self._applied_schema_version:
            return
        tgt_ct = await self.target._table(self.table, refresh=True)
        src_cols = {c.name: c for c in src_ct.info.schema.columns}
        tgt_cols = {c.name: c for c in tgt_ct.info.schema.columns}
        adds = [(c.name, c.type, getattr(c, "ql_type", None))
                for name, c in src_cols.items()
                if name not in tgt_cols and not c.is_key]
        drops = [name for name, c in tgt_cols.items()
                 if name not in src_cols and not c.is_key]
        if adds or drops:
            await self.target.alter_table(self.table, adds, drops)
        self._applied_schema_version = ver

    async def _step_inner(self) -> int:
        changes = await self.stream.poll()
        await self._maybe_replicate_ddl(changes)
        n = 0
        if changes:
            # one target write per source commit HT, applied AT that HT
            # (external hybrid time) so target reads at xCluster safe
            # time see exactly the source's consistent cut
            groups: List[Tuple[int, List[RowOp]]] = []

            async def flush_groups():
                nonlocal n
                for ht_, ops_ in groups:
                    await self.target.write(self.table, ops_,
                                            external_ht=ht_ or None)
                    self.replicated += len(ops_)
                    n += len(ops_)
                groups.clear()

            for c in changes:
                if c["op"] == "truncate":
                    # source TRUNCATE replicates as a target truncate
                    # at the same stream position — earlier changes
                    # must land first, later ones after.  One statement
                    # emits one WAL entry PER TABLET at one shared ht:
                    # apply once, skip the siblings (re-applying would
                    # wipe later rows already flushed to the target)
                    if c.get("ht") == getattr(self, "_last_truncate_ht",
                                              None):
                        continue
                    self._last_truncate_ht = c.get("ht")
                    await flush_groups()
                    await self.target.truncate_table(self.table)
                    continue
                op = RowOp("delete" if c["op"] == "delete" else "upsert",
                           c["row"])
                ht = c.get("ht", 0)
                if groups and groups[-1][0] == ht:
                    groups[-1][1].append(op)
                else:
                    groups.append((ht, [op]))
            await flush_groups()
        # checkpoint persists only after the target accepted the batch
        await self.stream.commit_checkpoints()
        await self._publish_safe_time()
        return n

    async def _publish_safe_time(self) -> None:
        """Advertise the replicated-up-to HT on the TARGET master so
        target-universe readers can take a consistent read_ht
        (reference: XClusterSafeTimeService publishing to the sys
        catalog)."""
        st = self.stream.safe_time()
        if not st:
            return
        try:
            await self.target._master_call(
                "set_xcluster_safe_time", {"table": self.table, "safe_ht": st})
        except (RpcError, asyncio.TimeoutError, OSError):
            pass

    async def start(self):
        await self.ensure_target_table()
        self._running = True
        self._task = asyncio.create_task(self._loop())

    async def _loop(self):
        while self._running:
            try:
                await self.step()
            except (RpcError, asyncio.TimeoutError, OSError):
                pass
            await asyncio.sleep(self.poll_interval)

    async def stop(self):
        self._running = False
        await cancel_and_drain(self._task)
        self._task = None
