"""CDC-SDK consumer API: replication slots over a virtual WAL.

The virtual WAL merges every tablet's change stream into ONE totally
ordered, resumable stream of transactions — the logical-decoding shape
(reference: src/yb/cdc/cdcsdk_virtual_wal.cc InitVirtualWALInternal/
GetConsistentChangesInternal, cdc_state_table.cc for slot persistence,
cdc_service.cc GetChanges as the per-tablet feed).

Design (TPU-framework idiom: the per-tablet feeds stay simple Raft-log
scans; ordering is a host-side merge with an explicit watermark):

- Every record carries an LSN `[commit_ht, txn_key, seq]`, compared
  lexicographically. LSNs are CONTENT-derived (commit hybrid time +
  stable txn key + position inside the txn), so a replay after a crash
  reproduces byte-identical LSNs — that is what makes `confirm_flush`
  exactly-once filtering sound.
- A transaction is emitted only once the watermark — min over every
  live tablet's safe hybrid time — passes its commit HT. A tablet's
  safe time does not advance while it still has buffered provisional
  records whose commit/abort we have not consumed, which (with HLC
  propagation) guarantees no later-arriving commit can order below the
  watermark: emission order is final.
- Tablet splits ride the stream itself: the parent's Raft log yields a
  `split` marker behind the write fence, after which the parent is
  retired and the children adopted at checkpoint 0. Pre-split changes
  come from the parent's log, post-split changes from the children's —
  exactly once, ordered.
- `confirm_flush(lsn)` persists per-tablet restart positions held back
  below every record of every UNCONFIRMED transaction, so a restarted
  consumer re-reads exactly what it has not acknowledged
  (at-least-once from the logs, exactly-once after LSN filtering).
"""
from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple

from ..client import YBClient
from ..rpc.messenger import RpcError


def _lsn_le(a, b) -> bool:
    return tuple(a) <= tuple(b)


class SlotInvalidError(Exception):
    """The slot's restart position was garbage-collected from a
    tablet's WAL: the stream cannot resume losslessly; the consumer
    must re-bootstrap (full snapshot copy) and create a fresh slot."""


class _TxnBuf:
    __slots__ = ("ops", "commit_ht", "pending_tids", "min_idx")

    def __init__(self):
        self.ops: List[dict] = []          # {"op","row","table"}
        self.commit_ht: Optional[int] = None
        # tablets whose provisional records for this txn are buffered
        # and whose own apply/rollback marker has not been consumed yet
        self.pending_tids: set = set()
        self.min_idx: Dict[str, int] = {}  # tid -> lowest log index used


class VirtualWal:
    def __init__(self, client: YBClient, slot_id: str, slot: dict):
        self.client = client
        self.slot_id = slot_id
        self.tables: List[str] = list(slot["tables"])
        self.confirmed_lsn = slot.get("confirmed_lsn")
        self._start_from = slot.get("start_from", "earliest")
        # tid -> {"table","checkpoint","retired","addrs"}
        self.tablets: Dict[str, dict] = {
            tid: dict(st) for tid, st in slot.get("state", {}).items()}
        self._safe_ht: Dict[str, int] = {}
        self._txns: Dict[str, _TxnBuf] = {}
        # txn decisions, kept until provably no replay can need them —
        # in particular decisions a split routed into the CHILDREN's
        # logs while the intents sit in the PARENT's log: txn_id ->
        # [commit_ht | None(=abort), tid, marker_index]. Persisted with
        # the slot (confirm_flush) so a restarted consumer can resolve
        # replayed parent intents without re-reading child markers whose
        # positions were already passed.
        self._decisions: Dict[str, list] = {
            k: list(v) for k, v in slot.get("decisions", {}).items()}
        # emitted but not yet confirmed: commit_lsn -> {tid: min_idx}
        self._unconfirmed: List[Tuple[list, Dict[str, int]]] = []

    # --- lifecycle -------------------------------------------------------
    @classmethod
    async def create(cls, client: YBClient, tables: List[str],
                     name: Optional[str] = None,
                     start_from: str = "earliest") -> "VirtualWal":
        r = await client._master_call(
            "create_replication_slot",
            {"name": name, "tables": list(tables),
             "start_from": start_from})
        vw = cls(client, r["slot_id"],
                 {"tables": tables, "state": {}, "confirmed_lsn": None,
                  "start_from": start_from})
        await vw._discover_tablets()
        if start_from == "now":
            for tid, st in vw.tablets.items():
                if st.get("retired"):
                    continue
                resp = await vw._get_changes(tid, {"tablet_id": tid,
                                                   "from_index": -1})
                st["checkpoint"] = resp["checkpoint"]
        # persist the initial tablet set NOW (not at first confirm):
        # the state entry is what makes the master RETAIN a parent that
        # splits before the consumer's first confirm (hidden-tablet
        # protection keys off slots whose state references the parent),
        # and for start_from="now" it pins the tail positions a crashed
        # consumer must not lose
        await client._master_call(
            "update_replication_slot",
            {"slot_id": vw.slot_id,
             "state": {t: dict(s) for t, s in vw.tablets.items()},
             "confirmed_lsn": None})
        return vw

    @classmethod
    async def attach(cls, client: YBClient, slot_id: str) -> "VirtualWal":
        r = await client._master_call("get_replication_slot",
                                      {"slot_id": slot_id})
        vw = cls(client, slot_id, r)
        await vw._discover_tablets()
        return vw

    async def drop(self) -> None:
        await self.client._master_call("drop_replication_slot",
                                       {"slot_id": self.slot_id})

    async def _discover_tablets(self) -> None:
        """Adopt tablets currently in the catalog for the slot's tables.
        Tablets already tracked (including retired split parents) keep
        their state; new ones (splits we have not seen markers for yet
        start from their own log head = 0) are added."""
        for name in self.tables:
            ct = await self.client._table(name, refresh=True)
            for loc in ct.locations:
                st = self.tablets.setdefault(
                    loc.tablet_id,
                    {"table": name, "checkpoint": 0, "retired": False,
                     "addrs": []})
                st["addrs"] = [list(a) for _, a in loc.replicas]

    # --- per-tablet feed -------------------------------------------------
    async def _get_changes(self, tid: str, payload: dict) -> dict:
        """get_changes routed first through the meta cache (live
        tablets), then by the slot's remembered replica addresses (split
        parents leave the catalog but their peers keep serving the log
        until retirement)."""
        st = self.tablets[tid]
        try:
            ct = await self.client._table(st["table"])
            if not any(l.tablet_id == tid for l in ct.locations):
                # a fresh child won't be in a stale cache: refresh once
                # so LIVE tablets always reach their LEADER (a follower
                # would answer with a useless safe_ht and stall the
                # watermark); only retired parents take the raw-address
                # fallback below
                ct = await self.client._table(st["table"], refresh=True)
            if any(l.tablet_id == tid for l in ct.locations):
                resp = await self.client._call_leader(
                    ct, tid, "get_changes", payload)
                loc = next(l for l in ct.locations if l.tablet_id == tid)
                st["addrs"] = [list(a) for _, a in loc.replicas]
                return resp
        except RpcError as e:
            if e.code == "CACHE_MISS_ERROR":
                raise
        last: Optional[Exception] = None
        for addr in st.get("addrs", []):
            try:
                return await self.client.messenger.call(
                    tuple(addr), "tserver", "get_changes", payload,
                    timeout=10.0)
            except RpcError as e:
                if e.code == "CACHE_MISS_ERROR":
                    raise
                last = e
            except (asyncio.TimeoutError, OSError) as e:
                last = e
        raise last or RpcError(f"tablet {tid} unreachable",
                               "SERVICE_UNAVAILABLE")

    def _tid_has_pending(self, tid: str) -> bool:
        return any(tid in t.pending_tids for t in self._txns.values())

    async def _poll_tablet(self, tid: str, limit: int) -> None:
        st = self.tablets[tid]
        try:
            resp = await self._get_changes(
                tid, {"tablet_id": tid,
                      "from_index": st["checkpoint"], "limit": limit})
        except RpcError as e:
            if e.code == "CACHE_MISS_ERROR":
                raise SlotInvalidError(
                    f"slot {self.slot_id}: WAL GC passed the restart "
                    f"position of tablet {tid}; re-bootstrap required"
                ) from e
            return                       # transiently unreachable
        except (asyncio.TimeoutError, OSError):
            return
        table = st["table"]
        for ch in resp["changes"]:
            op = ch["op"]
            if op == "split":
                st["retired"] = True
                st["checkpoint"] = ch["index"]
                st["split_index"] = ch["index"]
                self._safe_ht.pop(tid, None)
                # children: pre-split data came from THIS log; their own
                # logs hold only post-split writes, so checkpoint 0
                for cid in ch["children"]:
                    self.tablets.setdefault(
                        cid, {"table": table, "checkpoint": 0,
                              "retired": False, "addrs": list(st["addrs"])})
                # every provisional op of this parent is now buffered
                # (the marker is its last entry): txns still waiting on
                # the parent's own apply marker will get it from the
                # CHILDREN instead (the tserver routes decisions there)
                # — or already did (decision recorded below)
                for key, t in list(self._txns.items()):
                    if tid not in t.pending_tids:
                        continue
                    t.pending_tids.discard(tid)
                    if key in self._decisions:
                        dec = self._decisions[key]
                        if dec[0] is None:
                            if not t.pending_tids:
                                del self._txns[key]
                        else:
                            t.commit_ht = dec[0]
                    else:
                        t.pending_tids.update(ch["children"])
                return                  # nothing orders after the fence
            elif ch.get("provisional"):
                dec = self._decisions.get(ch["txn_id"])
                if dec is not None and dec[0] is None:
                    continue            # already known aborted
                t = self._txns.setdefault(ch["txn_id"], _TxnBuf())
                t.ops.append({"op": op, "row": ch["row"], "table": table,
                              "tid": tid, "sub": ch.get("sub", 0)})
                t.pending_tids.add(tid)
                t.min_idx[tid] = min(t.min_idx.get(tid, ch["index"]),
                                     ch["index"])
                if dec is not None:
                    t.commit_ht = dec[0]
            elif op == "abort_sub":
                # ROLLBACK TO SAVEPOINT: drop this txn's buffered ops
                # from THIS tablet with sub >= from_sub.  Per-tablet
                # scope is what makes this exact: the tablet's log
                # orders its discarded intents before the marker and
                # any post-rollback (fresh-subtxn) intents after it
                # (reference: aborted-SubtxnSet filtering in
                # cdc/cdcsdk_producer.cc)
                t = self._txns.get(ch["txn_id"])
                if t is not None:
                    t.ops = [o for o in t.ops
                             if not (o.get("tid") == tid
                                     and o.get("sub", 0)
                                     >= ch["from_sub"])]
            elif op == "commit":
                self._decisions.setdefault(
                    ch["txn_id"], [ch["ht"], tid, ch["index"]])
                t = self._txns.get(ch["txn_id"])
                if t is not None:
                    t.commit_ht = ch["ht"]
                    t.pending_tids.discard(tid)
            elif op == "abort":
                self._decisions.setdefault(
                    ch["txn_id"], [None, tid, ch["index"]])
                t = self._txns.get(ch["txn_id"])
                if t is not None:
                    t.pending_tids.discard(tid)
                    if not t.pending_tids:
                        del self._txns[ch["txn_id"]]
            elif op == "truncate":
                # TRUNCATE streams as ONE logical record (PG logical
                # replication emits one TRUNCATE message): the N
                # per-tablet WAL entries share a statement ht, so they
                # merge into a single txn keyed by it
                key = "tr-%s-%d" % (table, ch["ht"])
                t = self._txns.setdefault(key, _TxnBuf())
                if not t.ops:
                    t.ops.append({"op": "TRUNCATE", "row": None,
                                  "table": table})
                t.commit_ht = ch["ht"]
                t.min_idx[tid] = min(t.min_idx.get(tid, ch["index"]),
                                     ch["index"])
            else:
                # plain committed write: a singleton auto-applied txn
                # keyed by its log position (stable across replays)
                key = "w-%s-%d-%d" % (tid, ch["index"], ch["ht"])
                t = self._txns.setdefault(key, _TxnBuf())
                t.ops.append({"op": op, "row": ch["row"], "table": table})
                t.commit_ht = ch["ht"]
                t.min_idx[tid] = min(t.min_idx.get(tid, ch["index"]),
                                     ch["index"])
        st["checkpoint"] = max(st["checkpoint"], resp["checkpoint"])
        if not st["retired"] and not self._tid_has_pending(tid) \
                and resp.get("safe_ht"):
            self._safe_ht[tid] = max(self._safe_ht.get(tid, 0),
                                     resp["safe_ht"])

    # --- the consumer API ------------------------------------------------
    def _watermark(self) -> int:
        live = [tid for tid, st in self.tablets.items()
                if not st.get("retired")]
        if not live or any(tid not in self._safe_ht for tid in live):
            return 0
        return min(self._safe_ht[tid] for tid in live)

    async def get_consistent_changes(self, limit_per_tablet: int = 1000
                                     ) -> List[dict]:
        """One poll round + emission: returns BEGIN/ops/COMMIT records
        for every transaction whose commit HT has passed the watermark,
        in commit order, LSN-stamped. May return []."""
        for tid in list(self.tablets):
            st = self.tablets[tid]
            # a retired split parent is still polled while its restart
            # position sits below its split marker: confirm_flush held
            # it back there precisely so a restarted consumer re-reads
            # the parent txns it never acknowledged
            if not st.get("retired") or \
                    st["checkpoint"] < st.get("split_index", 0):
                await self._poll_tablet(tid, limit_per_tablet)
        wm = self._watermark()
        ready = sorted(
            (k for k, t in self._txns.items()
             if t.commit_ht is not None and not t.pending_tids
             and t.commit_ht <= wm),
            key=lambda k: (self._txns[k].commit_ht, k))
        out: List[dict] = []
        for key in ready:
            t = self._txns.pop(key)
            ht = t.commit_ht
            recs = [{"lsn": [ht, key, 0], "op": "BEGIN",
                     "txn": key, "commit_ht": ht}]
            for i, o in enumerate(t.ops):
                recs.append({"lsn": [ht, key, i + 1], "txn": key,
                             "commit_ht": ht, **o})
            recs.append({"lsn": [ht, key, len(t.ops) + 1], "op": "COMMIT",
                         "txn": key, "commit_ht": ht})
            if (self.confirmed_lsn is not None
                    and _lsn_le(recs[-1]["lsn"], self.confirmed_lsn)):
                continue                 # replayed + already confirmed
            self._unconfirmed.append((recs[-1]["lsn"], dict(t.min_idx)))
            out.extend(recs)
        return out

    async def confirm_flush(self, lsn) -> None:
        """Acknowledge everything up to `lsn` (a record's LSN, usually
        the last COMMIT processed downstream). Persists the slot so a
        restarted consumer resumes exactly past it."""
        self.confirmed_lsn = list(lsn)
        self._unconfirmed = [
            (clsn, idx) for clsn, idx in self._unconfirmed
            if not _lsn_le(clsn, lsn)]
        state = {}
        for tid, st in self.tablets.items():
            cp = st["checkpoint"]
            # hold below anything a replay still needs: records of
            # emitted-but-unconfirmed txns and of still-buffered ones
            for _, idx in self._unconfirmed:
                if tid in idx:
                    cp = min(cp, idx[tid] - 1)
            for t in self._txns.values():
                if tid in t.min_idx:
                    cp = min(cp, t.min_idx[tid] - 1)
            state[tid] = {**st, "checkpoint": cp}
        # Decision release: a decision is only needed while a replay
        # could re-deliver the txn's provisional ops WITHOUT their
        # markers — which (same-log ordering: ops precede markers)
        # happens only via a retired parent whose restart position is
        # still below its split marker. While ANY such replay region
        # exists, every decision stays: even a confirmed txn's intents
        # can sit above another txn's held-back position and replay
        # without them would re-buffer the txn undecidably, freezing
        # the watermark.
        replay_region = any(
            s.get("retired")
            and s["checkpoint"] < s.get("split_index", 0)
            for s in state.values())
        if not replay_region:
            for key in list(self._decisions):
                if key not in self._txns:
                    del self._decisions[key]
        await self.client._master_call(
            "update_replication_slot",
            {"slot_id": self.slot_id, "state": state,
             "confirmed_lsn": self.confirmed_lsn,
             "decisions": self._decisions})
