from .consumer import CdcStream, XClusterReplicator  # noqa: F401
from .virtual_wal import SlotInvalidError, VirtualWal  # noqa: F401
