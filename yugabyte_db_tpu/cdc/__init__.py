from .consumer import CdcStream, XClusterReplicator  # noqa: F401
