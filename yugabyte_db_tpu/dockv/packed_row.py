"""Packed rows: a whole row as a single KV value, columnar-decode friendly.

Reference: src/yb/dockv/packed_row.h (RowPackerV1/V2),
src/yb/dockv/schema_packing.h:77 (SchemaPacking — schema-version-keyed
column layout with fixed/varlen offsets). SURVEY.md calls this "the
columnar-decode seam for TPU", and the format here is designed for that:

    [varint schema_version]
    [null bitmap  ceil(n/8) bytes]
    [fixed region: one always-present slot per fixed-width column]
    [varlen offsets: u32 LE *end* offset per varlen column]
    [varlen heap]

Everything before the heap has a fixed per-schema stride, so decoding N
rows is: stack prefixes into an [N, stride] uint8 matrix and reinterpret
column slices — no per-row branching, directly feedable to numpy/JAX.
(The reference's V2 format has the same spirit; bytes differ.)
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .key_encoding import _decode_varint_unsigned, _encode_varint_unsigned
from .value import PrimitiveValue, ValueKind


class ColumnType:
    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    TIMESTAMP = "timestamp"   # int64 micros
    STRING = "string"
    BINARY = "binary"
    JSON = "json"
    DECIMAL = "decimal"       # stored as string for now
    VECTOR = "vector"         # float32 array (pgvector analog)

    FIXED_WIDTHS = {
        BOOL: 1, INT32: 4, INT64: 8, FLOAT32: 4, FLOAT64: 8, TIMESTAMP: 8,
    }
    NUMPY_DTYPES = {
        BOOL: np.uint8, INT32: np.dtype("<i4"), INT64: np.dtype("<i8"),
        FLOAT32: np.dtype("<f4"), FLOAT64: np.dtype("<f8"),
        TIMESTAMP: np.dtype("<i8"),
    }

    @staticmethod
    def is_fixed(t: str) -> bool:
        return t in ColumnType.FIXED_WIDTHS


_PACK_FMT = {
    ColumnType.BOOL: "<B", ColumnType.INT32: "<i", ColumnType.INT64: "<q",
    ColumnType.FLOAT32: "<f", ColumnType.FLOAT64: "<d",
    ColumnType.TIMESTAMP: "<q",
}


@dataclass(frozen=True)
class ColumnSchema:
    id: int                   # stable column id (never reused)
    name: str
    type: str
    nullable: bool = True
    is_hash_key: bool = False
    is_range_key: bool = False
    sort_desc: bool = False   # range column sort order
    # original query-layer type when richer than the storage type —
    # e.g. a CQL collection ("list<text>") stored as JSON. Persisted in
    # the catalog so wire servers recover element typing after restart
    # (reference: QLTypePB params in common/ql_type.proto)
    ql_type: "str | None" = None
    # serial/bigserial: the owned sequence feeding this column's
    # INSERT default (reference: PG pg_attrdef nextval defaults)
    default_seq: "str | None" = None
    # literal DEFAULT applied when INSERT omits the column
    # (reference: PG pg_attrdef)
    default_value: object = None

    @property
    def is_key(self) -> bool:
        return self.is_hash_key or self.is_range_key


@dataclass(frozen=True)
class TableSchema:
    """Table schema (reference: src/yb/common/schema.h). Column order:
    hash key columns, then range key columns, then value columns."""

    columns: Tuple[ColumnSchema, ...]
    version: int = 0

    def __post_init__(self):
        ids = [c.id for c in self.columns]
        if len(set(ids)) != len(ids):
            raise ValueError("duplicate column ids")

    @property
    def key_columns(self) -> List[ColumnSchema]:
        return [c for c in self.columns if c.is_key]

    @property
    def hash_columns(self) -> List[ColumnSchema]:
        return [c for c in self.columns if c.is_hash_key]

    @property
    def range_columns(self) -> List[ColumnSchema]:
        return [c for c in self.columns if c.is_range_key]

    @property
    def value_columns(self) -> List[ColumnSchema]:
        return [c for c in self.columns if not c.is_key]

    def column_by_name(self, name: str) -> ColumnSchema:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)

    def column_by_id(self, cid: int) -> ColumnSchema:
        for c in self.columns:
            if c.id == cid:
                return c
        raise KeyError(cid)


@dataclass
class SchemaPacking:
    """Layout of the packed form of one schema version's value columns
    (reference: dockv/schema_packing.h:77)."""

    schema_version: int
    fixed_columns: List[ColumnSchema] = field(default_factory=list)
    varlen_columns: List[ColumnSchema] = field(default_factory=list)
    # derived:
    fixed_offsets: Dict[int, int] = field(default_factory=dict)  # col id -> offset
    fixed_size: int = 0
    bitmap_size: int = 0
    prefix_size: int = 0      # varint(header) excluded; bitmap+fixed+offsets

    @classmethod
    def from_schema(cls, schema: TableSchema) -> "SchemaPacking":
        sp = cls(schema_version=schema.version)
        for c in schema.value_columns:
            (sp.fixed_columns if ColumnType.is_fixed(c.type)
             else sp.varlen_columns).append(c)
        off = 0
        for c in sp.fixed_columns:
            sp.fixed_offsets[c.id] = off
            off += ColumnType.FIXED_WIDTHS[c.type]
        sp.fixed_size = off
        n = len(sp.fixed_columns) + len(sp.varlen_columns)
        sp.bitmap_size = (n + 7) // 8
        sp.prefix_size = sp.bitmap_size + sp.fixed_size + 4 * len(sp.varlen_columns)
        return sp

    @property
    def all_columns(self) -> List[ColumnSchema]:
        return self.fixed_columns + self.varlen_columns

    def null_bit_index(self, cid: int) -> int:
        for i, c in enumerate(self.all_columns):
            if c.id == cid:
                return i
        raise KeyError(cid)


class RowPacker:
    """Packs value columns into a single packed-row value
    (reference: dockv/packed_row.h:285,311 RowPackerV1/V2). The hot
    path runs in C (native/ybtpu_hot.c Packer) when every column type
    is in its supported set; exotic types (json/decimal/vector carry
    pre-encoded values with looser typing) keep the Python packer.
    Outputs are byte-identical; invalid values fail loudly on both
    paths, though the exception CLASS may differ (struct.error on the
    Python path vs TypeError/OverflowError natively)."""

    _NATIVE_FIXED = {ColumnType.BOOL: "?", ColumnType.INT32: "i",
                     ColumnType.INT64: "q", ColumnType.TIMESTAMP: "q",
                     ColumnType.FLOAT32: "f", ColumnType.FLOAT64: "d"}
    _NATIVE_VARLEN = {ColumnType.STRING: 1, ColumnType.BINARY: 2}

    def __init__(self, packing: SchemaPacking):
        self.packing = packing
        self._header = _encode_varint_unsigned(packing.schema_version)
        self._native = False            # built lazily on first pack

    def _native_packer(self):
        if self._native is False:
            self._native = None
            from ..storage.columnar import native_hot
            hot = native_hot()
            if hot is not None and hasattr(hot, "Packer"):
                p = self.packing
                plan = []
                # the C packer's bitmap scratch caps at 64 bytes (512
                # columns); wider schemas keep the Python path
                ok = p.bitmap_size <= 64
                for c in p.all_columns:
                    if c.type in self._NATIVE_FIXED:
                        plan.append((c.id, 0, self._NATIVE_FIXED[c.type],
                                     p.fixed_offsets[c.id]))
                    elif c.type in self._NATIVE_VARLEN:
                        plan.append((c.id, self._NATIVE_VARLEN[c.type],
                                     "s", 0))
                    else:
                        ok = False
                        break
                if ok:
                    try:
                        self._native = hot.Packer(
                            bytes(self._header), plan, p.bitmap_size,
                            p.fixed_size, len(p.varlen_columns))
                    except Exception:
                        self._native = None
        return self._native

    def pack(self, values: Dict[int, object]) -> bytes:
        """values: column id -> python value (None for NULL)."""
        nat = self._native_packer()
        if nat is not None:
            return nat.pack(values)
        p = self.packing
        bitmap = bytearray(p.bitmap_size)
        fixed = bytearray(p.fixed_size)
        offsets = bytearray()
        heap = bytearray()
        for i, c in enumerate(p.all_columns):
            v = values.get(c.id)
            if v is None:
                bitmap[i // 8] |= 1 << (i % 8)
        for c in p.fixed_columns:
            v = values.get(c.id)
            off = p.fixed_offsets[c.id]
            w = ColumnType.FIXED_WIDTHS[c.type]
            if v is not None:
                if c.type == ColumnType.BOOL:
                    v = int(bool(v))
                struct.pack_into(_PACK_FMT[c.type], fixed, off, v)
        for c in p.varlen_columns:
            v = values.get(c.id)
            if v is not None:
                raw = v.encode() if isinstance(v, str) else bytes(v)
                heap += raw
            offsets += struct.pack("<I", len(heap))
        return bytes(self._header + bitmap + fixed + offsets + heap)

    def pack_value(self, values: Dict[int, object]) -> bytes:
        """Full KV value: kPackedRowV2 marker + packed bytes."""
        return bytes([ValueKind.kPackedRowV2]) + self.pack(values)


def unpack_row(packing: SchemaPacking, data: bytes,
               start: int = 0) -> Dict[int, object]:
    """Row-at-a-time unpack (CPU path). The columnar batch decode lives in
    storage/columnar.py and ops/."""
    p = packing
    ver, pos = _decode_varint_unsigned(data, start)
    if ver != p.schema_version:
        raise ValueError(f"schema version mismatch: {ver} != {p.schema_version}")
    bitmap = data[pos:pos + p.bitmap_size]
    pos += p.bitmap_size
    fixed = data[pos:pos + p.fixed_size]
    pos += p.fixed_size
    nvar = len(p.varlen_columns)
    ends = struct.unpack_from(f"<{nvar}I", data, pos) if nvar else ()
    pos += 4 * nvar
    heap = data[pos:]
    out: Dict[int, object] = {}
    for i, c in enumerate(p.all_columns):
        if bitmap[i // 8] & (1 << (i % 8)):
            out[c.id] = None
            continue
        if ColumnType.is_fixed(c.type):
            v = struct.unpack_from(_PACK_FMT[c.type], fixed,
                                   p.fixed_offsets[c.id])[0]
            if c.type == ColumnType.BOOL:
                v = bool(v)
            out[c.id] = v
        else:
            vi = i - len(p.fixed_columns)
            lo = ends[vi - 1] if vi else 0
            raw = bytes(heap[lo:ends[vi]])
            out[c.id] = raw.decode() if c.type in (
                ColumnType.STRING, ColumnType.JSON, ColumnType.DECIMAL) else raw
    return out


class SchemaPackingStorage:
    """schema_version -> SchemaPacking registry, kept per table
    (reference: dockv/schema_packing.h SchemaPackingStorage). Old versions
    are retained until compaction repacks all rows to the latest."""

    def __init__(self):
        self._packings: Dict[int, SchemaPacking] = {}

    def add_schema(self, schema: TableSchema) -> SchemaPacking:
        sp = SchemaPacking.from_schema(schema)
        self._packings[schema.version] = sp
        return sp

    def get(self, version: int) -> SchemaPacking:
        return self._packings[version]

    def version_of(self, packed: bytes, start: int = 0) -> int:
        ver, _ = _decode_varint_unsigned(packed, start)
        return ver

    def versions(self) -> List[int]:
        return sorted(self._packings)
