from .key_encoding import (  # noqa: F401
    ValueType, KeyBytes, KeyEntryValue,
    encode_key_entry, decode_key_entry,
    DocKey, SubDocKey,
)
from .value import PrimitiveValue, ValueKind  # noqa: F401
from .partition import PartitionSchema, Partition, hash_key_for  # noqa: F401
from .packed_row import (  # noqa: F401
    ColumnType, ColumnSchema, TableSchema, SchemaPacking,
    RowPacker, unpack_row, SchemaPackingStorage,
)
