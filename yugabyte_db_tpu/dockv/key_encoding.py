"""Order-preserving doc key encoding.

The design follows the reference's DocKey/SubDocKey model (reference:
src/yb/dockv/doc_key.h:40-60,95): a doc key is

    [hash prefix: type byte + 16-bit hash] [hashed components...] GroupEnd
    [range components...] GroupEnd

and a SubDocKey appends subkeys plus a DESCENDING-encoded DocHybridTime so
that newer versions of the same document sort first (reference:
src/yb/dockv/key_bytes.h, src/yb/common/doc_hybrid_time.cc).

Every component is encoded with a leading type byte chosen so that raw
`memcmp` of encoded keys equals typed comparison of the decoded tuples —
the single invariant the whole LSM depends on. The byte values and the
zero-escaping scheme are our own; only the *property* matches the
reference.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Tuple

from ..utils.hybrid_time import DocHybridTime, ENCODED_SIZE


class ValueType:
    """Type bytes for key components, ordered so encodings sort correctly.

    (Analog of reference dockv::KeyEntryType, src/yb/dockv/value_type.h.)
    """
    # Structure markers sort BELOW all value types so that a prefix key
    # (fewer components) sorts before any extension of it — same property
    # as the reference's kGroupEnd='!' sitting below its letter-valued
    # types (src/yb/dockv/value_type.h).
    kLowest = 0x01
    kGroupEnd = 0x03
    kHybridTime = 0x05
    kUInt16Hash = 0x08   # 2-byte big-endian hash prefix (key start only)
    kCoTableId = 0x0A    # 4-byte BE colocated-table id (key start only)
    # value types
    kNull = 0x20
    kFalse = 0x22
    kTrue = 0x23
    kInt32 = 0x24
    kInt64 = 0x26
    kDouble = 0x28
    kString = 0x2A
    kTimestamp = 0x2C
    kBytes = 0x2E
    kUuid = 0x32
    # descending variants (= kX + 0x20): payload bytes complemented
    kInt32Desc = 0x44
    kInt64Desc = 0x46
    kDoubleDesc = 0x48
    kStringDesc = 0x4A
    kTimestampDesc = 0x4C
    kBytesDesc = 0x4E
    kNullDesc = 0x5E
    kColumnId = 0x6B
    kSystemColumnId = 0x6C
    kIntentPrefix = 0x70  # intents-db key space marker
    kTransactionId = 0x71
    kHighest = 0x7F

_DESC_OFFSET = 0x20  # kXDesc = kX + 0x20 for orderable types


def _encode_int_key(v: int, width: int) -> bytes:
    """Sign-flipped big-endian: memcmp order == numeric order."""
    bias = 1 << (width * 8 - 1)
    return (v + bias).to_bytes(width, "big")


def _decode_int_key(data: bytes, width: int) -> int:
    bias = 1 << (width * 8 - 1)
    return int.from_bytes(data[:width], "big") - bias


def _encode_double_key(v: float) -> bytes:
    bits = struct.unpack(">Q", struct.pack(">d", v))[0]
    if bits & (1 << 63):
        bits ^= (1 << 64) - 1      # negative: flip all bits
    else:
        bits |= 1 << 63            # positive: flip sign bit
    return bits.to_bytes(8, "big")


def _decode_double_key(data: bytes) -> float:
    bits = int.from_bytes(data[:8], "big")
    if bits & (1 << 63):
        bits &= (1 << 63) - 1
    else:
        bits ^= (1 << 64) - 1
    return struct.unpack(">d", struct.pack(">Q", bits))[0]


def _escape_zeros(data: bytes) -> bytes:
    """'\\x00' -> '\\x00\\x01'; terminator '\\x00\\x00' sorts before any
    continuation, making prefix-freedom hold (reference scheme:
    src/yb/dockv/key_bytes.h AppendString)."""
    return data.replace(b"\x00", b"\x00\x01") + b"\x00\x00"


def _unescape_zeros(data: bytes) -> Tuple[bytes, int]:
    out = bytearray()
    i = 0
    while i < len(data):
        b = data[i]
        if b == 0:
            nxt = data[i + 1]
            if nxt == 0:
                return bytes(out), i + 2
            if nxt == 1:
                out.append(0)
                i += 2
                continue
            raise ValueError("bad zero escape in key")
        out.append(b)
        i += 1
    raise ValueError("unterminated string in key")


def _complement(data: bytes) -> bytes:
    return bytes(b ^ 0xFF for b in data)


@dataclass(frozen=True)
class KeyEntryValue:
    """One typed key component. kind is 'null'|'bool'|'int32'|'int64'|
    'double'|'string'|'bytes'|'timestamp'|'column_id'; desc flips sort order.
    """
    kind: str
    value: object = None
    desc: bool = False

    # convenience constructors
    @staticmethod
    def null(desc: bool = False): return KeyEntryValue("null", None, desc)
    @staticmethod
    def int32(v: int, desc: bool = False): return KeyEntryValue("int32", v, desc)
    @staticmethod
    def int64(v: int, desc: bool = False): return KeyEntryValue("int64", v, desc)
    @staticmethod
    def double(v: float, desc: bool = False): return KeyEntryValue("double", v, desc)
    @staticmethod
    def string(v: str, desc: bool = False): return KeyEntryValue("string", v, desc)
    @staticmethod
    def raw_bytes(v: bytes, desc: bool = False): return KeyEntryValue("bytes", v, desc)
    @staticmethod
    def bool_(v: bool): return KeyEntryValue("bool", v)
    @staticmethod
    def timestamp(micros: int, desc: bool = False):
        return KeyEntryValue("timestamp", micros, desc)
    @staticmethod
    def column_id(cid: int): return KeyEntryValue("column_id", cid)


def encode_key_entry(e: KeyEntryValue) -> bytes:
    d = e.desc
    if e.kind == "null":
        return bytes([ValueType.kNullDesc if d else ValueType.kNull])
    if e.kind == "bool":
        return bytes([ValueType.kTrue if e.value else ValueType.kFalse])
    if e.kind == "int32":
        p = _encode_int_key(e.value, 4)
        return bytes([ValueType.kInt32Desc if d else ValueType.kInt32]) + (
            _complement(p) if d else p)
    if e.kind == "int64":
        p = _encode_int_key(e.value, 8)
        return bytes([ValueType.kInt64Desc if d else ValueType.kInt64]) + (
            _complement(p) if d else p)
    if e.kind == "double":
        p = _encode_double_key(e.value)
        return bytes([ValueType.kDoubleDesc if d else ValueType.kDouble]) + (
            _complement(p) if d else p)
    if e.kind == "timestamp":
        p = _encode_int_key(e.value, 8)
        return bytes([ValueType.kTimestampDesc if d else ValueType.kTimestamp]) + (
            _complement(p) if d else p)
    if e.kind in ("string", "bytes"):
        raw = e.value.encode() if e.kind == "string" else e.value
        p = _escape_zeros(raw)
        t = ValueType.kString if e.kind == "string" else ValueType.kBytes
        if d:
            return bytes([t + _DESC_OFFSET]) + _complement(p)
        return bytes([t]) + p
    if e.kind == "column_id":
        return bytes([ValueType.kColumnId]) + _encode_varint_unsigned(e.value)
    raise ValueError(f"unknown key entry kind {e.kind}")


def decode_key_entry(data: bytes, pos: int) -> Tuple[KeyEntryValue, int]:
    t = data[pos]
    pos += 1
    V = ValueType
    if t == V.kNull:
        return KeyEntryValue.null(), pos
    if t == V.kNullDesc:
        return KeyEntryValue.null(desc=True), pos
    if t == V.kFalse:
        return KeyEntryValue.bool_(False), pos
    if t == V.kTrue:
        return KeyEntryValue.bool_(True), pos
    if t in (V.kInt32, V.kInt32Desc):
        desc = t == V.kInt32Desc
        raw = data[pos:pos + 4]
        if desc:
            raw = _complement(raw)
        return KeyEntryValue.int32(_decode_int_key(raw, 4), desc), pos + 4
    if t in (V.kInt64, V.kInt64Desc, V.kTimestamp, V.kTimestampDesc):
        desc = t in (V.kInt64Desc, V.kTimestampDesc)
        raw = data[pos:pos + 8]
        if desc:
            raw = _complement(raw)
        v = _decode_int_key(raw, 8)
        if t in (V.kTimestamp, V.kTimestampDesc):
            return KeyEntryValue.timestamp(v, desc), pos + 8
        return KeyEntryValue.int64(v, desc), pos + 8
    if t in (V.kDouble, V.kDoubleDesc):
        desc = t == V.kDoubleDesc
        raw = data[pos:pos + 8]
        if desc:
            raw = _complement(raw)
        return KeyEntryValue.double(_decode_double_key(raw), desc), pos + 8
    if t in (V.kString, V.kBytes):
        raw, consumed = _unescape_zeros(data[pos:])
        kind = "string" if t == V.kString else "bytes"
        v = raw.decode() if kind == "string" else raw
        return KeyEntryValue(kind, v), pos + consumed
    if t in (V.kString + _DESC_OFFSET, V.kBytes + _DESC_OFFSET):
        # find complemented terminator 0xFF 0xFF with escapes 0xFF 0xFE
        sub = data[pos:]
        comp = _complement(sub)  # cheap: keys are short
        raw, consumed = _unescape_zeros(comp)
        kind = "string" if t == V.kString + _DESC_OFFSET else "bytes"
        v = raw.decode() if kind == "string" else raw
        return KeyEntryValue(kind, v, desc=True), pos + consumed
    if t == V.kColumnId:
        v, pos = _decode_varint_unsigned(data, pos)
        return KeyEntryValue.column_id(v), pos
    raise ValueError(f"unknown key entry type byte {t:#x} at {pos - 1}")


def _encode_varint_unsigned(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _decode_varint_unsigned(data: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    v = 0
    while True:
        b = data[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, pos
        shift += 7


class KeyBytes:
    """Mutable encoded-key builder (reference: src/yb/dockv/key_bytes.h)."""

    def __init__(self, data: bytes = b""):
        self._buf = bytearray(data)

    def append_entry(self, e: KeyEntryValue) -> "KeyBytes":
        self._buf += encode_key_entry(e)
        return self

    def append_group_end(self) -> "KeyBytes":
        self._buf.append(ValueType.kGroupEnd)
        return self

    def append_hash(self, h: int) -> "KeyBytes":
        self._buf.append(ValueType.kUInt16Hash)
        self._buf += h.to_bytes(2, "big")
        return self

    def append_hybrid_time(self, dht: DocHybridTime) -> "KeyBytes":
        self._buf.append(ValueType.kHybridTime)
        self._buf += dht.encoded_desc()
        return self

    def append_raw(self, data: bytes) -> "KeyBytes":
        self._buf += data
        return self

    def data(self) -> bytes:
        return bytes(self._buf)

    def __len__(self):
        return len(self._buf)


@dataclass(frozen=True)
class DocKey:
    """Primary-key portion of a row key (reference: dockv/doc_key.h:95;
    colocated tables carry a cotable prefix, doc_key.h:40-60)."""

    hash: Optional[int] = None                 # 16-bit partition hash
    hashed: Tuple[KeyEntryValue, ...] = ()
    range: Tuple[KeyEntryValue, ...] = ()
    cotable_id: Optional[int] = None           # colocated table id

    @classmethod
    def make(cls, hash: Optional[int] = None,
             hashed: Iterable[KeyEntryValue] = (),
             range: Iterable[KeyEntryValue] = (),
             cotable_id: Optional[int] = None) -> "DocKey":
        return cls(hash, tuple(hashed), tuple(range), cotable_id)

    def encode(self) -> bytes:
        kb = KeyBytes()
        if self.cotable_id is not None:
            kb.append_raw(bytes([ValueType.kCoTableId])
                          + self.cotable_id.to_bytes(4, "big"))
        if self.hash is not None:
            kb.append_hash(self.hash)
            for e in self.hashed:
                kb.append_entry(e)
            kb.append_group_end()
        for e in self.range:
            kb.append_entry(e)
        kb.append_group_end()
        return kb.data()

    @classmethod
    def decode(cls, data: bytes, pos: int = 0) -> Tuple["DocKey", int]:
        hash_ = None
        hashed: List[KeyEntryValue] = []
        range_: List[KeyEntryValue] = []
        cotable = None
        if pos < len(data) and data[pos] == ValueType.kCoTableId:
            cotable = int.from_bytes(data[pos + 1:pos + 5], "big")
            pos += 5
        if pos < len(data) and data[pos] == ValueType.kUInt16Hash:
            hash_ = int.from_bytes(data[pos + 1:pos + 3], "big")
            pos += 3
            while data[pos] != ValueType.kGroupEnd:
                e, pos = decode_key_entry(data, pos)
                hashed.append(e)
            pos += 1
        while pos < len(data) and data[pos] != ValueType.kGroupEnd:
            e, pos = decode_key_entry(data, pos)
            range_.append(e)
        if pos >= len(data) or data[pos] != ValueType.kGroupEnd:
            raise ValueError("doc key missing range group end")
        return cls(hash_, tuple(hashed), tuple(range_), cotable), pos + 1


@dataclass(frozen=True)
class SubDocKey:
    """DocKey + subkeys (e.g. a column id) + DocHybridTime.

    Reference: src/yb/dockv/doc_key.h SubDocKey. The encoded form is what
    actually lands in the LSM: `doc_key subkeys kHybridTime ht_desc`.
    """

    doc_key: DocKey
    subkeys: Tuple[KeyEntryValue, ...] = ()
    doc_ht: Optional[DocHybridTime] = None

    def encode(self, include_ht: bool = True) -> bytes:
        kb = KeyBytes(self.doc_key.encode())
        for s in self.subkeys:
            kb.append_entry(s)
        if include_ht and self.doc_ht is not None:
            kb.append_hybrid_time(self.doc_ht)
        return kb.data()

    @classmethod
    def decode(cls, data: bytes) -> "SubDocKey":
        dk, pos = DocKey.decode(data)
        subkeys: List[KeyEntryValue] = []
        dht = None
        while pos < len(data):
            if data[pos] == ValueType.kHybridTime:
                dht = DocHybridTime.decode_desc(data[pos + 1:pos + 1 + ENCODED_SIZE])
                pos += 1 + ENCODED_SIZE
                break
            e, pos = decode_key_entry(data, pos)
            subkeys.append(e)
        return cls(dk, tuple(subkeys), dht)


def split_key_ht(encoded: bytes) -> Tuple[bytes, DocHybridTime]:
    """Split an encoded SubDocKey into (key prefix without HT, DocHybridTime).

    The HT suffix has fixed size, so this is O(1) — the hot path for MVCC
    visibility checks and compaction GC.
    """
    marker_pos = len(encoded) - ENCODED_SIZE - 1
    if marker_pos < 0 or encoded[marker_pos] != ValueType.kHybridTime:
        raise ValueError("key has no hybrid time suffix")
    return encoded[:marker_pos], DocHybridTime.decode_desc(encoded[marker_pos + 1:])
