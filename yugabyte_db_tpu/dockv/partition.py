"""Hash and range partitioning of tables into tablets.

Reference: src/yb/dockv/partition.h — a PartitionSchema maps a row's key to
a 16-bit hash; tablets own contiguous ranges of hash space (or ranges of
encoded range keys for range-sharded tables). Docs:
architecture/docdb-sharding/sharding.md.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from .key_encoding import DocKey, KeyEntryValue, encode_key_entry

MAX_HASH = 0x10000  # 16-bit hash space, like the reference

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_M64 = (1 << 64) - 1


def hash_key_for(entries: Sequence[KeyEntryValue]) -> int:
    """Deterministic 16-bit hash of the hashed key components.

    The reference uses YBPartition::HashColumnCompoundValue (Jenkins); we
    use FNV-1a over the order-preserving encoding, folded to 16 bits —
    chosen because it is equally computable per-row here and in bulk with
    numpy (dockv/bulk.py fast_hash16_from_encoded must agree bit-for-bit).
    """
    h = _FNV_OFFSET
    for e in entries:
        for b in encode_key_entry(e):
            h = ((h ^ b) * _FNV_PRIME) & _M64
    h ^= h >> 32
    return h & 0xFFFF


@dataclass(frozen=True)
class Partition:
    """One tablet's key-space slice: [start, end) over the partition key.

    For hash-sharded tables the bounds are 2-byte big-endian hash values;
    empty bytes mean -inf / +inf (reference: dockv/partition.h Partition).
    """

    start: bytes = b""
    end: bytes = b""

    def contains(self, partition_key: bytes) -> bool:
        if self.start and partition_key < self.start:
            return False
        if self.end and partition_key >= self.end:
            return False
        return True

    def __repr__(self):
        s = self.start.hex() or "-inf"
        e = self.end.hex() or "+inf"
        return f"Partition[{s},{e})"


@dataclass(frozen=True)
class PartitionSchema:
    """How a table splits into tablets.

    kind: 'hash' (16-bit multi-column hash) or 'range' (encoded range key).
    num_hash_columns tells how many leading PK columns are hashed; the rest
    are range columns within the tablet.
    """

    kind: str = "hash"
    num_hash_columns: int = 1

    def partition_key_for_row(self, pk_entries: Sequence[KeyEntryValue]) -> bytes:
        if self.kind == "hash":
            h = hash_key_for(pk_entries[: self.num_hash_columns])
            return h.to_bytes(2, "big")
        out = bytearray()
        for e in pk_entries:
            out += encode_key_entry(e)
        return bytes(out)

    def doc_key_for_row(self, pk_entries: Sequence[KeyEntryValue]) -> DocKey:
        if self.kind == "hash":
            n = self.num_hash_columns
            return DocKey.make(hash=hash_key_for(pk_entries[:n]),
                               hashed=pk_entries[:n], range=pk_entries[n:])
        return DocKey.make(range=pk_entries)

    def create_partitions(self, num_tablets: int,
                          split_points: Optional[List[bytes]] = None
                          ) -> List[Partition]:
        """Even hash-space split (reference:
        PartitionSchema::CreateHashPartitions) or explicit range split
        points."""
        if self.kind == "range":
            points = split_points or []
            bounds = [b""] + list(points) + [b""]
            return [Partition(bounds[i], bounds[i + 1])
                    for i in range(len(bounds) - 1)]
        step = MAX_HASH // num_tablets
        parts = []
        for i in range(num_tablets):
            start = (i * step).to_bytes(2, "big") if i else b""
            end = ((i + 1) * step).to_bytes(2, "big") if i + 1 < num_tablets else b""
            parts.append(Partition(start, end))
        return parts


def split_partition(p: Partition, split_key: Optional[bytes] = None
                    ) -> Tuple[Partition, Partition]:
    """Split a partition at split_key (or the hash midpoint) — the core of
    automatic tablet splitting (reference: tablet/operations/split_operation.cc,
    master/tablet_split_manager.cc)."""
    if split_key is None:
        lo = int.from_bytes(p.start or b"\x00\x00", "big")
        hi = int.from_bytes(p.end or b"\xff\xff", "big") if p.end else MAX_HASH
        mid = (lo + hi) // 2
        split_key = mid.to_bytes(2, "big")
    if (p.start and split_key <= p.start) or (p.end and split_key >= p.end):
        raise ValueError("split key outside partition")
    return Partition(p.start, split_key), Partition(split_key, p.end)
