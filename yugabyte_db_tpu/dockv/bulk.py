"""Vectorized (numpy) doc-key encoding/decoding for bulk ingest and
columnar block builds.

The reference encodes keys row-at-a-time in C++ (fast enough on CPU); our
hot paths instead batch-encode whole columns with numpy so block builds
and bulk loads never drop into a per-row Python loop. Byte format is
identical to key_encoding.py (asserted by tests).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .key_encoding import ValueType
from .partition import MAX_HASH


def encode_int64_column(values: np.ndarray, desc: bool = False) -> np.ndarray:
    """[N] int64 -> [N, 9] uint8 of kInt64-typed order-preserving encoding."""
    v = values.astype(np.int64, copy=False)
    biased = (v.astype(np.uint64) + np.uint64(1 << 63)).astype(">u8")
    raw = biased.view(np.uint8).reshape(-1, 8)
    t = ValueType.kInt64
    if desc:
        raw = raw ^ np.uint8(0xFF)
        t = ValueType.kInt64Desc
    out = np.empty((len(v), 9), np.uint8)
    out[:, 0] = t
    out[:, 1:] = raw
    return out


def encode_int32_column(values: np.ndarray, desc: bool = False) -> np.ndarray:
    v = values.astype(np.int32, copy=False)
    biased = (v.astype(np.int64) + (1 << 31)).astype(">u4")
    raw = biased.view(np.uint8).reshape(-1, 4)
    t = ValueType.kInt32
    if desc:
        raw = raw ^ np.uint8(0xFF)
        t = ValueType.kInt32Desc
    out = np.empty((len(v), 5), np.uint8)
    out[:, 0] = t
    out[:, 1:] = raw
    return out


def encode_double_column(values: np.ndarray, desc: bool = False) -> np.ndarray:
    bits = values.astype(np.float64, copy=False).view(np.uint64)
    neg = (bits >> np.uint64(63)).astype(bool)
    flipped = np.where(neg, ~bits, bits | np.uint64(1 << 63)).astype(">u8")
    raw = flipped.view(np.uint8).reshape(-1, 8)
    t = ValueType.kDouble
    if desc:
        raw = raw ^ np.uint8(0xFF)
        t = ValueType.kDoubleDesc
    out = np.empty((len(values), 9), np.uint8)
    out[:, 0] = t
    out[:, 1:] = raw
    return out


_ENCODERS = {
    "int64": encode_int64_column,
    "int32": encode_int32_column,
    "float64": encode_double_column,
    "timestamp": lambda v, desc=False: _retype(
        encode_int64_column(v, desc),
        ValueType.kTimestampDesc if desc else ValueType.kTimestamp),
}


def _retype(block: np.ndarray, t: int) -> np.ndarray:
    block[:, 0] = t
    return block


def hash16_int64_column(values: np.ndarray) -> np.ndarray:
    """Vectorized 16-bit partition hash of single-int64 hash keys.

    Must agree with partition.hash_key_for for int64 entries; we use a
    splitmix64-style mix of the 9 encoded bytes. To keep cross-impl
    agreement simple, partition.hash_key_for is the definition (blake2b);
    here we call it via a vectorized python fallback only for small N and
    a cached table for benchmarks.  For bulk loads we instead use
    `fast_hash16`, a numpy-only hash, and the scalar path in
    partition_fast.py matches it.
    """
    return fast_hash16_from_encoded(encode_int64_column(values))


def fast_hash16_from_encoded(enc: np.ndarray) -> np.ndarray:
    """FNV-1a over encoded key component bytes, folded to 16 bits.

    This (not blake2b) is the engine-wide partition hash used by
    PartitionSchema when `fast_hash=True`; it exists so the hash is
    computable both per-row in Python and in bulk in numpy.
    """
    h = np.full(enc.shape[0], np.uint64(0xCBF29CE484222325))
    prime = np.uint64(0x100000001B3)
    for j in range(enc.shape[1]):
        h = (h ^ enc[:, j].astype(np.uint64)) * prime
    h ^= h >> np.uint64(32)
    return (h & np.uint64(0xFFFF)).astype(np.uint32)


def fast_hash16_bytes(data: bytes) -> int:
    """Scalar twin of fast_hash16_from_encoded (single key)."""
    h = 0xCBF29CE484222325
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    h ^= h >> 32
    return h & 0xFFFF


def encode_doc_keys(hash_values: Optional[np.ndarray],
                    component_blocks: Sequence[np.ndarray],
                    num_hash_components: int = 0) -> np.ndarray:
    """Build [N, L] uint8 encoded DocKeys from per-component encoded blocks.

    hash_values: uint16 partition hashes (or None for range-sharded keys).
    component_blocks: output of encode_*_column per PK component, in order.
    """
    n = component_blocks[0].shape[0] if component_blocks else len(hash_values)
    parts: List[np.ndarray] = []
    if hash_values is not None:
        hdr = np.empty((n, 3), np.uint8)
        hdr[:, 0] = ValueType.kUInt16Hash
        hv = hash_values.astype(">u2").view(np.uint8).reshape(-1, 2)
        hdr[:, 1:] = hv
        parts.append(hdr)
        parts.extend(component_blocks[:num_hash_components])
        ge = np.full((n, 1), ValueType.kGroupEnd, np.uint8)
        parts.append(ge)
    parts.extend(component_blocks[num_hash_components:])
    parts.append(np.full((n, 1), ValueType.kGroupEnd, np.uint8))
    return np.concatenate(parts, axis=1)


def append_hybrid_times(doc_keys: np.ndarray, ht_values: np.ndarray,
                        write_ids: np.ndarray) -> np.ndarray:
    """[N, L] keys + per-row DocHybridTime -> [N, L+13] encoded SubDocKeys
    (kHybridTime marker + 12-byte descending-encoded (ht, write_id))."""
    n = doc_keys.shape[0]
    marker = np.full((n, 1), ValueType.kHybridTime, np.uint8)
    ht_be = (~ht_values.astype(np.uint64)).astype(">u8").view(np.uint8).reshape(-1, 8)
    wid_be = (~write_ids.astype(np.uint32)).astype(">u4").view(np.uint8).reshape(-1, 4)
    return np.concatenate([doc_keys, marker, ht_be, wid_be], axis=1)


#: packable integer component types -> their STORAGE dtype. Values must
#: wrap through the storage dtype before biasing, exactly like the
#: byte encoders do (encode_int32_column casts via astype(np.int32)),
#: or out-of-range inputs would sort differently than their encodings.
_PACKABLE_TYPES = {"int32": np.int32, "int64": np.int64,
                   "timestamp": np.int64}


def bulk_sort_order(hash_values: Optional[np.ndarray],
                    components: Sequence[tuple],
                    doc_keys: np.ndarray) -> np.ndarray:
    """Sort order of N rows by encoded-doc-key byte order, computed from
    the ORIGINAL numeric columns instead of a row-wise byte matrix.

    components: [(values, type_name, desc)] per PK component, in key
    order. For integer-typed components the order-preserving encoding is
    a monotone byte mapping, so the key order equals the numeric order —
    and when the value ranges fit, every component packs into ONE uint64
    whose single radix argsort beats the generic void-dtype comparison
    sort on the encoded matrix ~3x (the bulk-ingest hot sort).

    Falls back to the byte-matrix argsort for non-integer or
    wide-range keys; byte order is always the ground truth."""
    parts: List[np.ndarray] = []
    spans: List[int] = []
    if hash_values is not None:
        parts.append(hash_values.astype(np.uint64))
        spans.append(1 << 16)
    ok = len(doc_keys) > 0
    if ok:
        for values, tname, desc in components:
            dtype = _PACKABLE_TYPES.get(tname)
            if dtype is None:
                ok = False
                break
            u = (np.asarray(values).astype(dtype).astype(np.int64)
                 .astype(np.uint64) + np.uint64(1 << 63))
            if desc:
                u = ~u
            lo = u.min()
            u = u - lo
            span = int(u.max()) + 1
            parts.append(u)
            spans.append(span)
    if ok and parts:
        total_bits = sum(max(1, int(s - 1).bit_length()) for s in spans)
        if total_bits <= 63:
            packed = np.zeros(len(doc_keys), np.uint64)
            for u, s in zip(parts, spans):
                packed = (packed << np.uint64(
                    max(1, int(s - 1).bit_length()))) | u
            return np.argsort(packed, kind="stable")
        if len(parts) <= 3:
            return np.lexsort(tuple(reversed(parts)))
    v = np.ascontiguousarray(doc_keys).view(
        np.dtype((np.void, doc_keys.shape[1]))).reshape(-1)
    return np.argsort(v, kind="stable")


def keys_to_bytes_list(enc: np.ndarray) -> List[bytes]:
    """Materialize row-wise byte strings (host-side boundary ops only)."""
    flat = enc.tobytes()
    w = enc.shape[1]
    return [flat[i * w:(i + 1) * w] for i in range(enc.shape[0])]
