"""Primitive value encoding for the value side of KV pairs.

Analog of the reference's PrimitiveValue (reference:
src/yb/dockv/primitive_value.cc) minus the key-encoding half, which lives
in key_encoding.py. Values don't need order preservation, so encodings are
compact little-endian.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional, Tuple


class ValueKind:
    kNull = 0x00
    kFalse = 0x01
    kTrue = 0x02
    kInt32 = 0x03
    kInt64 = 0x04
    kDouble = 0x05
    kFloat = 0x06
    kString = 0x07
    kBytes = 0x08
    kTimestamp = 0x09
    kDecimal = 0x0A
    kJson = 0x0B
    kTombstone = 0x10        # row/cell deletion marker
    kPackedRowV1 = 0x20      # row-as-single-KV, nested-values format
    kPackedRowV2 = 0x21      # row-as-single-KV, columnar-friendly format
    kMergeFlags = 0x30       # TTL etc. prefix
    kRowLock = 0x31          # lock-only intent value


TTL_HDR_LEN = 9   # kMergeFlags marker + u64 expire hybrid time


def wrap_ttl(value: bytes, expire_ht: int) -> bytes:
    """Prefix a KV value with an expiration hybrid time (reference: TTL
    merge flags in dockv value encoding)."""
    return bytes([ValueKind.kMergeFlags]) + struct.pack("<Q", expire_ht) + value


def unwrap_ttl(value: bytes):
    """Returns (inner_value, expire_ht or None)."""
    if value and value[0] == ValueKind.kMergeFlags:
        (exp,) = struct.unpack_from("<Q", value, 1)
        return value[TTL_HDR_LEN:], exp
    return value, None


@dataclass(frozen=True)
class PrimitiveValue:
    kind: int
    value: object = None

    @staticmethod
    def null(): return PrimitiveValue(ValueKind.kNull)
    @staticmethod
    def tombstone(): return PrimitiveValue(ValueKind.kTombstone)
    @staticmethod
    def int32(v): return PrimitiveValue(ValueKind.kInt32, int(v))
    @staticmethod
    def int64(v): return PrimitiveValue(ValueKind.kInt64, int(v))
    @staticmethod
    def double(v): return PrimitiveValue(ValueKind.kDouble, float(v))
    @staticmethod
    def string(v): return PrimitiveValue(ValueKind.kString, str(v))
    @staticmethod
    def raw_bytes(v): return PrimitiveValue(ValueKind.kBytes, bytes(v))
    @staticmethod
    def bool_(v): return PrimitiveValue(ValueKind.kTrue if v else ValueKind.kFalse)
    @staticmethod
    def timestamp(us): return PrimitiveValue(ValueKind.kTimestamp, int(us))

    def is_tombstone(self) -> bool:
        return self.kind == ValueKind.kTombstone

    def to_python(self):
        if self.kind == ValueKind.kTrue:
            return True
        if self.kind == ValueKind.kFalse:
            return False
        if self.kind in (ValueKind.kNull, ValueKind.kTombstone):
            return None
        return self.value

    def encode(self) -> bytes:
        k = self.kind
        if k in (ValueKind.kNull, ValueKind.kTombstone, ValueKind.kTrue,
                 ValueKind.kFalse, ValueKind.kRowLock):
            return bytes([k])
        if k == ValueKind.kInt32:
            return bytes([k]) + struct.pack("<i", self.value)
        if k in (ValueKind.kInt64, ValueKind.kTimestamp):
            return bytes([k]) + struct.pack("<q", self.value)
        if k == ValueKind.kDouble:
            return bytes([k]) + struct.pack("<d", self.value)
        if k == ValueKind.kFloat:
            return bytes([k]) + struct.pack("<f", self.value)
        if k == ValueKind.kString:
            return bytes([k]) + self.value.encode()
        if k in (ValueKind.kBytes, ValueKind.kJson,
                 ValueKind.kPackedRowV1, ValueKind.kPackedRowV2):
            return bytes([k]) + self.value
        raise ValueError(f"cannot encode value kind {k:#x}")

    @classmethod
    def decode(cls, data: bytes) -> "PrimitiveValue":
        k = data[0]
        body = data[1:]
        if k in (ValueKind.kNull, ValueKind.kTombstone, ValueKind.kTrue,
                 ValueKind.kFalse, ValueKind.kRowLock):
            return cls(k)
        if k == ValueKind.kInt32:
            return cls(k, struct.unpack("<i", body[:4])[0])
        if k in (ValueKind.kInt64, ValueKind.kTimestamp):
            return cls(k, struct.unpack("<q", body[:8])[0])
        if k == ValueKind.kDouble:
            return cls(k, struct.unpack("<d", body[:8])[0])
        if k == ValueKind.kFloat:
            return cls(k, struct.unpack("<f", body[:4])[0])
        if k == ValueKind.kString:
            return cls(k, body.decode())
        if k in (ValueKind.kBytes, ValueKind.kJson,
                 ValueKind.kPackedRowV1, ValueKind.kPackedRowV2):
            return cls(k, bytes(body))
        raise ValueError(f"cannot decode value kind {k:#x}")
