"""Cross-process chaos controller.

Arms the existing utils/fault_injection.py machinery in CHILD
processes (via the servers' ``arm_fault`` control RPC or the
supervisor's env handshake), then kills peers and stalls disks on a
SEEDED schedule — the same round replays identically given the same
seed and cluster shape, so a chaos failure is reproducible instead of
anecdotal (reference analog: the ExternalMiniCluster crash itests +
TEST_ flag fault points, run against real forked daemons).

An event is a plain tuple so plans are printable/serializable:

    ("kill",       victim, at_s)            SIGKILL, no drain code runs
    ("disk_stall", victim, at_s, stall_s)   storage write path hangs
    ("crash_point", victim, at_s, name)     armed hard -> process dies
                                            at the named product seam
    ("restart",    victim, at_s)            respawn with backoff
"""
from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from .supervisor import ClusterSupervisor


@dataclass(frozen=True)
class ChaosEvent:
    kind: str                     # kill | disk_stall | crash_point | restart
    victim: str                   # managed-process name (ts-i)
    at_s: float                   # offset into the round
    arg: Optional[object] = None  # stall seconds / crash-point name

    def as_tuple(self) -> tuple:
        return (self.kind, self.victim, self.at_s) + (
            (self.arg,) if self.arg is not None else ())


class ChaosController:
    def __init__(self, sup: ClusterSupervisor, seed: int = 0):
        self.sup = sup
        self.seed = seed
        self.rng = random.Random(seed)
        self.executed: List[tuple] = []

    def plan_round(self, kills: int = 1, stalls: int = 1,
                   stall_s: float = 1.0, round_s: float = 2.0,
                   spare: Sequence[str] = (),
                   restart_after_s: float = 0.5) -> List[ChaosEvent]:
        """Derive one round's schedule from the seed: victims and times
        are rng-chosen from the CURRENT tserver set (minus `spare` —
        e.g. the node a test needs alive), kills get a paired restart.
        Deterministic: same seed + same cluster shape = same plan."""
        candidates = sorted(n for n in self.sup.tserver_names()
                            if n not in spare)
        if not candidates:
            raise ValueError("no chaos candidates (all spared)")
        events: List[ChaosEvent] = []
        kill_victims = []
        for _ in range(min(kills, len(candidates))):
            v = self.rng.choice([c for c in candidates
                                 if c not in kill_victims] or candidates)
            at = round(self.rng.uniform(0.1, max(0.2, round_s / 2)), 3)
            kill_victims.append(v)
            events.append(ChaosEvent("kill", v, at))
            events.append(ChaosEvent("restart", v,
                                     round(at + restart_after_s, 3)))
        for _ in range(stalls):
            # stall a peer that is NOT being killed when possible: a
            # dead process can't exercise its storage path
            alive = [c for c in candidates if c not in kill_victims]
            v = self.rng.choice(alive or candidates)
            at = round(self.rng.uniform(0.1, max(0.2, round_s / 2)), 3)
            events.append(ChaosEvent("disk_stall", v, at, stall_s))
        return sorted(events, key=lambda e: (e.at_s, e.kind, e.victim))

    async def run_round(self, events: Sequence[ChaosEvent]) -> List[tuple]:
        """Execute a planned round against the live cluster.  Waits are
        relative to the round start; the executed log (with outcomes)
        is returned and kept on the controller for the bench record.
        Each event is contained: a failed arm/restart (e.g. a stall
        aimed at a peer that is dead right now, or a READY timeout on
        a slow box) logs an error outcome and the round CONTINUES —
        losing the paired restart to an earlier event's failure would
        turn one transient error into a wedged cluster."""
        t0 = time.monotonic()
        log: List[tuple] = []
        for ev in sorted(events, key=lambda e: e.at_s):
            delay = ev.at_s - (time.monotonic() - t0)
            if delay > 0:
                await asyncio.sleep(delay)
            try:
                outcome = await self._execute(ev)
            except Exception as e:   # noqa: BLE001 — contained above
                outcome = f"error={type(e).__name__}: {str(e)[:80]}"
            log.append(ev.as_tuple() + (outcome,))
            self.executed.append(log[-1])
        return log

    async def _execute(self, ev: ChaosEvent) -> str:
        if ev.kind == "kill":
            code = await self.sup.kill(ev.victim)
            return f"exit={code}"
        if ev.kind == "restart":
            await self.sup.restart(ev.victim)
            return "ready"
        if ev.kind == "disk_stall":
            stall_s = float(ev.arg) if ev.arg is not None else 1.0
            await self.sup.call(ev.victim, "tserver", "arm_fault",
                                {"disk_stall_s": stall_s},
                                timeout=10.0)
            return f"stalled={stall_s}s"
        if ev.kind == "crash_point":
            await self.sup.call(ev.victim, "tserver", "arm_fault",
                                {"crash_points": [str(ev.arg)],
                                 "hard": True}, timeout=10.0)
            return f"armed={ev.arg}"
        raise ValueError(f"unknown chaos event kind {ev.kind!r}")

    async def clear_all(self) -> None:
        """Disarm every fault on every live server (round teardown)."""
        await self.sup.call_all("arm_fault", {"clear_all": True},
                                best_effort=True)
