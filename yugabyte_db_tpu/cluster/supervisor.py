"""ClusterSupervisor: real OS processes under one orchestrator.

The multi-process analog of tools/mini_cluster.py (reference:
integration-tests/external_mini_cluster.h, the forked-daemon harness):
spawns yb-master/yb-tserver/driver analogs via spawn-safe module entry
points (``python -m yugabyte_db_tpu.tools.server_main`` /
``...cluster.driver``), gives each its own data dir and log file,
gates on a readiness barrier, and exposes the two stop shapes a real
deployment has — SIGTERM drain (flush + WAL close + lease release,
exit 0) and SIGKILL crash — plus restart with exponential backoff.

Supervisor protocol (CLUSTER.md):

- layout: ``<root>/<name>/`` data dir per process,
  ``<root>/logs/<name>.log`` capturing stdout+stderr;
- readiness: the child prints ``READY <host>:<port>`` as its first
  line (into its log file); the supervisor polls the log, so no pipe
  management can deadlock a wedged child — and a child that dies
  before READY fails fast with its log tail in the error;
- ports: first spawn binds port 0 (the OS chooses); restarts rebind
  the SAME endpoint, because Raft configs and client caches address
  nodes by host:port;
- control: the supervisor holds a client-side Messenger and reaches
  children through their normal RPC services (set_flag, arm_fault,
  metrics_snapshot, ...) — there is no second control channel to
  drift from the real one.
"""
from __future__ import annotations

import asyncio
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..rpc.messenger import Messenger, RpcError
from ..utils.tasks import cancel_and_drain, drain_all

_READY_PREFIX = "READY "


@dataclass
class ManagedProcess:
    """One supervised child: its spawn recipe (for restarts) + state."""

    name: str
    role: str                          # master | tserver | driver
    module: str
    args: List[str]
    env: Dict[str, str]
    log_path: str
    data_dir: str
    proc: Optional[subprocess.Popen] = None
    addr: Optional[Tuple[str, int]] = None
    port: int = 0                      # pinned after first readiness
    restarts: int = 0
    stopped: bool = False              # deliberate stop (monitor ignores)
    #: byte offset up to which the (append-only) log has been scanned
    #: for READY lines: each incarnation prints exactly one, so a
    #: restart's barrier only sees FRESH lines past this offset — and
    #: each poll reads O(new bytes), not the whole history
    log_scan_pos: int = 0
    _fail_streak: int = field(default=0, repr=False)
    _last_start: float = field(default=0.0, repr=False)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def exit_code(self) -> Optional[int]:
        return None if self.proc is None else self.proc.poll()


class ClusterSupervisor:
    """Spawn and drive a master + N tservers (+ driver processes).

    Async context manager::

        sup = await ClusterSupervisor(root, num_tservers=3).start()
        try:
            drv = await sup.spawn_driver("drv-0")
            ...
        finally:
            await sup.shutdown()
    """

    #: restart backoff schedule (seconds) indexed by the current
    #: consecutive-fast-failure streak, capped at the last entry
    BACKOFF_S = (0.0, 0.25, 0.5, 1.0, 2.0, 5.0)
    #: a child alive at least this long resets its failure streak
    STABLE_UPTIME_S = 5.0

    def __init__(self, root: str, num_tservers: int = 2,
                 zones: Optional[List[str]] = None,
                 auto_balance: bool = False,
                 env: Optional[Dict[str, str]] = None,
                 ready_timeout_s: float = 60.0):
        self.root = str(root)
        self.num_tservers = num_tservers
        self.zones = zones
        self.auto_balance = auto_balance
        self.ready_timeout_s = ready_timeout_s
        self.procs: Dict[str, ManagedProcess] = {}
        self.messenger = Messenger("cluster-supervisor")
        self._monitor_task: Optional[asyncio.Task] = None
        self._base_env = dict(os.environ)
        self._base_env.setdefault("YBTPU_PLATFORM", "cpu")
        # the repo root must be importable in children no matter where
        # the supervisor itself was launched from
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        pp = self._base_env.get("PYTHONPATH", "")
        if pkg_root not in pp.split(os.pathsep):
            self._base_env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + pp if pp else ""))
        if env:
            self._base_env.update(env)

    # --- naming -----------------------------------------------------------
    def master_name(self) -> str:
        return "master-0"

    def tserver_names(self) -> List[str]:
        return [n for n, p in self.procs.items() if p.role == "tserver"]

    def master_addrs(self) -> List[Tuple[str, int]]:
        return [p.addr for p in self.procs.values()
                if p.role == "master" and p.addr is not None]

    def _masters_arg(self) -> str:
        return ",".join(f"{h}:{p}" for h, p in self.master_addrs())

    # --- spawning ---------------------------------------------------------
    def _spawn(self, mp: ManagedProcess, port: Optional[int] = None
               ) -> None:
        os.makedirs(os.path.dirname(mp.log_path), exist_ok=True)
        os.makedirs(mp.data_dir, exist_ok=True)
        args = list(mp.args)
        if port is not None:
            args += ["--port", str(port)]
        log = open(mp.log_path, "ab", buffering=0)
        try:
            mp.proc = subprocess.Popen(
                [sys.executable, "-m", mp.module] + args,
                stdout=log, stderr=subprocess.STDOUT, env=mp.env,
                start_new_session=True)
        finally:
            log.close()           # the child owns the fd now
        mp.stopped = False
        mp.addr = None
        mp._last_start = time.monotonic()

    def _make_proc(self, name: str, role: str, module: str,
                   args: List[str], extra_env: Optional[dict] = None
                   ) -> ManagedProcess:
        env = dict(self._base_env)
        if extra_env:
            env.update(extra_env)
        mp = ManagedProcess(
            name=name, role=role, module=module, args=args, env=env,
            log_path=os.path.join(self.root, "logs", f"{name}.log"),
            data_dir=os.path.join(self.root, name))
        self.procs[name] = mp
        return mp

    async def start(self) -> "ClusterSupervisor":
        name = self.master_name()
        args = ["master", "--fs-root",
                os.path.join(self.root, name), "--uuid", "m0"]
        if self.auto_balance:
            args.append("--auto-balance")
        mp = self._make_proc(name, "master",
                             "yugabyte_db_tpu.tools.server_main", args)
        self._spawn(mp, port=0)
        barriers: List[asyncio.Task] = []
        try:
            await self.wait_ready(name)
            # spawn every tserver FIRST, then gate: the children's
            # interpreter boots (the dominant startup cost) overlap
            names = [self._make_tserver(i).name
                     for i in range(self.num_tservers)]
            barriers = [asyncio.ensure_future(self.wait_ready(n))
                        for n in names]
            await asyncio.gather(*barriers)
            await self.wait_tservers_live()
        except BaseException:
            # gather leaves siblings running; drain so none outlives us
            await drain_all(barriers)
            # a failed barrier must not strand the children already
            # spawned (start_new_session detaches them from us): the
            # caller never got the supervisor back, so nobody else
            # can shut them down
            await self.shutdown()
            raise
        return self

    def _make_tserver(self, i: int, extra_env: Optional[dict] = None
                      ) -> ManagedProcess:
        name = f"ts-{i}"
        zone = (self.zones[i % len(self.zones)] if self.zones
                else "zone-default")
        mp = self._make_proc(
            name, "tserver", "yugabyte_db_tpu.tools.server_main",
            ["tserver", "--fs-root", os.path.join(self.root, name),
             "--uuid", name, "--masters", self._masters_arg(),
             "--zone", zone], extra_env)
        self._spawn(mp, port=0)
        return mp

    async def spawn_tserver(self, i: int,
                            extra_env: Optional[dict] = None
                            ) -> ManagedProcess:
        mp = self._make_tserver(i, extra_env)
        await self.wait_ready(mp.name)
        return mp

    async def spawn_driver(self, name: str,
                           extra_args: Optional[List[str]] = None,
                           extra_env: Optional[dict] = None
                           ) -> ManagedProcess:
        """A remote load-driver process (cluster/driver.py) wired at
        this cluster's masters; drive it through its `driver` RPC
        service."""
        mp = self._make_proc(
            name, "driver", "yugabyte_db_tpu.cluster.driver",
            ["--masters", self._masters_arg()] + list(extra_args or ()),
            extra_env)
        self._spawn(mp, port=0)
        await self.wait_ready(name)
        return mp

    # --- readiness barrier ------------------------------------------------
    def _tail(self, mp: ManagedProcess, n: int = 12) -> str:
        try:
            with open(mp.log_path, "r", errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return "<no log>"

    async def wait_ready(self, name: str,
                         timeout: Optional[float] = None) -> Tuple[str, int]:
        """Poll the child's log for a FRESH READY line (one past the
        scanned offset — restarts append, so the barrier can never
        accept the dead predecessor's line); fail fast (with the log
        tail) if the process dies first.  Each poll reads only the
        bytes appended since the last one."""
        mp = self.procs[name]
        deadline = time.monotonic() + (timeout or self.ready_timeout_s)
        while time.monotonic() < deadline:
            ready: Optional[str] = None
            try:
                # analysis-ok(async_blocking): reads only new bytes
                with open(mp.log_path, "rb") as f:
                    f.seek(mp.log_scan_pos)
                    chunk = f.read()
            except OSError:
                chunk = b""
            if chunk:
                # consume complete lines only: a partially-flushed
                # line stays unscanned for the next poll
                cut = chunk.rfind(b"\n") + 1
                for ln in chunk[:cut].decode(
                        errors="replace").splitlines():
                    if ln.startswith(_READY_PREFIX):
                        ready = ln
                mp.log_scan_pos += cut
            if ready is not None:
                host, port = ready[len(_READY_PREFIX):] \
                    .strip().rsplit(":", 1)
                mp.addr = (host, int(port))
                mp.port = mp.addr[1]
                return mp.addr
            if not mp.alive():
                raise RuntimeError(
                    f"{name} exited (code {mp.exit_code()}) before "
                    f"READY; log tail:\n{self._tail(mp)}")
            await asyncio.sleep(0.05)
        raise TimeoutError(f"{name} not READY after "
                           f"{timeout or self.ready_timeout_s}s; log "
                           f"tail:\n{self._tail(mp)}")

    async def wait_tservers_live(self, count: Optional[int] = None,
                                 timeout: float = 30.0) -> None:
        """Readiness barrier part 2: the master must see the tservers'
        heartbeats before tables can place replicas on them."""
        want = count if count is not None else len(self.tserver_names())
        maddr = self.master_addrs()[0]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                r = await self.messenger.call(maddr, "master",
                                              "list_tservers", {},
                                              timeout=5.0)
                live = sum(1 for d in r["tservers"].values()
                           if d.get("live"))
                if live >= want:
                    return
            except (RpcError, asyncio.TimeoutError, OSError):
                pass
            await asyncio.sleep(0.1)
        raise TimeoutError(f"{want} tservers not live at the master")

    # --- stop / crash / restart -------------------------------------------
    async def stop(self, name: str, drain: bool = True,
                   timeout: float = 20.0) -> int:
        """SIGTERM drain (the graceful path — exit code 0 means the
        flush+WAL-close drain completed) or SIGKILL crash."""
        mp = self.procs[name]
        mp.stopped = True
        if not mp.alive():
            return mp.exit_code() or 0
        mp.proc.send_signal(signal.SIGTERM if drain else signal.SIGKILL)
        code = await self._wait_exit(mp, timeout)
        if code is None:
            mp.proc.kill()
            code = await self._wait_exit(mp, 5.0)
        return code if code is not None else -9

    async def kill(self, name: str) -> int:
        """Crash fidelity: SIGKILL, no drain code runs at all."""
        return await self.stop(name, drain=False)

    async def _wait_exit(self, mp: ManagedProcess,
                         timeout: float) -> Optional[int]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            code = mp.proc.poll()
            if code is not None:
                return code
            await asyncio.sleep(0.05)
        return None

    async def restart(self, name: str, backoff: bool = True) -> None:
        """Respawn a child on ITS OWN port + data dir, applying the
        exponential backoff policy: fast consecutive failures (uptime
        under STABLE_UPTIME_S) back off exponentially; a stable run
        resets the streak."""
        mp = self.procs[name]
        if mp.alive():
            await self.stop(name)
        # the streak counts consecutive SHORT-LIVED incarnations: a
        # child that ran stably restarts with no delay (deliberate
        # chaos/test restarts must not accrue backoff), a fast-dying
        # one backs off exponentially
        uptime = time.monotonic() - mp._last_start
        if uptime >= self.STABLE_UPTIME_S:
            mp._fail_streak = 0
        else:
            mp._fail_streak += 1
        delay = self.backoff_delay(mp._fail_streak) if backoff else 0.0
        if delay > 0:
            await asyncio.sleep(delay)
        mp.restarts += 1
        self._spawn(mp, port=mp.port or 0)
        await self.wait_ready(name)

    @classmethod
    def backoff_delay(cls, streak: int) -> float:
        return cls.BACKOFF_S[min(streak, len(cls.BACKOFF_S) - 1)]

    async def start_monitor(self) -> None:
        """Auto-restart policy: watch for UNEXPECTED exits (not stopped
        through the supervisor) and restart with backoff — the chaos
        layer kills peers and this brings them back."""
        if self._monitor_task is None:
            self._monitor_task = asyncio.create_task(self._monitor())

    async def _monitor(self):
        while True:
            for name, mp in list(self.procs.items()):
                if mp.proc is not None and not mp.alive() \
                        and not mp.stopped:
                    try:
                        await self.restart(name)
                    except Exception:   # noqa: BLE001 — keep watching;
                        # the next sweep retries with a longer backoff
                        pass
            await asyncio.sleep(0.25)

    # --- control RPC ------------------------------------------------------
    async def call(self, name: str, service: str, method: str,
                   payload: dict, timeout: float = 30.0):
        mp = self.procs[name]
        if mp.addr is None:
            raise RuntimeError(f"{name} has no address (not ready)")
        return await self.messenger.call(mp.addr, service, method,
                                         payload, timeout=timeout)

    async def call_all(self, method: str, payload: dict,
                       roles: Tuple[str, ...] = ("tserver", "master"),
                       timeout: float = 10.0,
                       best_effort: bool = False) -> Dict[str, object]:
        """Broadcast one control RPC to every LIVE server process of
        the given roles (the role names double as their service
        names); returns {process name: response}.  best_effort
        contains per-server failures (teardown sweeps) instead of
        aborting the broadcast on the first dead-mid-call peer."""
        out: Dict[str, object] = {}
        for name, mp in self.procs.items():
            if mp.role not in roles or not mp.alive():
                continue
            try:
                out[name] = await self.call(name, mp.role, method,
                                            payload, timeout=timeout)
            except Exception:   # noqa: BLE001 — contained per spec
                if not best_effort:
                    raise
        return out

    async def set_flag_all(self, flag: str, value,
                           roles: Tuple[str, ...] = ("tserver", "master")
                           ) -> None:
        """Flip a runtime flag in every live server process (the
        cross-process analog of flags.set_flag in MiniCluster benches)."""
        await self.call_all("set_flag", {"name": flag, "value": value},
                            roles=roles)

    def client(self):
        """A YBClient wired at this cluster's masters (caller owns the
        messenger shutdown)."""
        from ..client import YBClient
        return YBClient(master_addrs=self.master_addrs())

    # --- teardown ---------------------------------------------------------
    async def shutdown(self, drain: bool = False) -> None:
        """Stop everything (drivers first, then tservers, then the
        master).  drain=True SIGTERMs; the default kills — tests that
        assert on the drain path call stop(name, drain=True) explicitly
        and check the exit code."""
        await cancel_and_drain(self._monitor_task)
        self._monitor_task = None
        order = {"driver": 0, "tserver": 1, "master": 2}
        for name, mp in sorted(self.procs.items(),
                               key=lambda kv: order.get(kv[1].role, 3)):
            try:
                await self.stop(name, drain=drain,
                                timeout=10.0 if drain else 5.0)
            except Exception:   # noqa: BLE001 — teardown best-effort
                if mp.proc is not None:
                    mp.proc.kill()
        await self.messenger.shutdown()
