"""Multi-process cluster harness (ISSUE 10 / ROADMAP "multi-process
cluster under live fire").

Everything else in this repo runs clients and servers on one event
loop and one GIL; this package spawns them as REAL OS processes —
N tservers + a master + remote load-driver processes — so availability
and load behavior can be engineered and measured the way Taurus
separates compute and storage into independently-failing processes.

Layering (enforced by the tools/analyze `layering` pass in tier-1):
``cluster/`` talks to servers ONLY over RPC and process signals — it
may import client/rpc/utils (and the model/request vocabulary) but
never ``tserver``/``tablet`` internals.
"""
from .chaos import ChaosController, ChaosEvent
from .collector import (attribute_rounds, collect_cluster_tracez,
                        dominant_wait, stitch, tree_names)
from .supervisor import ClusterSupervisor, ManagedProcess

__all__ = ["ChaosController", "ChaosEvent", "ClusterSupervisor",
           "ManagedProcess", "attribute_rounds",
           "collect_cluster_tracez", "dominant_wait", "stitch",
           "tree_names"]
