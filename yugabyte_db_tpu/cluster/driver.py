"""Open-loop YCSB load-driver process.

    python -m yugabyte_db_tpu.cluster.driver --masters host:port[,...]

A REMOTE client fleet as one real OS process (spawned by
cluster/supervisor.py): it owns a pool of YBClients on its own event
loop/GIL, fires an OPEN loop — ops are launched on the offered-rate
clock, never gated on completions, so server backpressure shows up as
latency/sheds instead of silently throttling the offered load — and
ships per-op latency histograms back to the supervisor over its
``driver`` RPC service:

- ``setup``      create + load the usertable (rows/tablets/RF knobs)
- ``saturation`` closed-loop probe: the rate the cluster sustains
- ``run_phase``  open loop at an offered rate with an SLA deadline;
                 returns p50/p95/p99, achieved (in-SLA) goodput, shed/
                 timeout counts; every acked write's full row token is
                 remembered for later verification
- ``verify``     quiesced re-read of every acked write, byte-compared
                 against what was acked (the chaos round's zero-data-
                 loss check)
- ``quit``       graceful exit

Layering: this module talks to the cluster ONLY through the public
client (tools/analyze `layering` forbids tserver/tablet imports here).
"""
from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..client import YBClient
from ..models.ycsb import usertable_info
from ..rpc.messenger import Messenger, RpcError
from ..utils.metrics import REGISTRY

#: fields written per row — the byte-verify compares every one
_N_FIELDS = 10
#: fresh write keys start here, far above any base-row key
_WRITE_KEY_BASE = 10_000_000

# transient faults an op can surface while the cluster splits, moves
# replicas, or loses a peer (client retry exhaustion includes OSError/
# RuntimeError, not just RpcError)
_TRANSIENT = (RpcError, asyncio.TimeoutError, OSError, RuntimeError)


def _row_token(tag: str, key: int) -> str:
    return f"{tag}:{key}:{'v' * 20}"


def _make_row(tag: str, key: int) -> dict:
    token = _row_token(tag, key)
    return {"ycsb_key": key,
            **{f"field{j}": token for j in range(_N_FIELDS)}}


class LoadDriver:
    """The in-process half: an RPC service over a YBClient pool."""

    def __init__(self, master_addrs: List[Tuple[str, int]],
                 n_clients: int = 8):
        self.master_addrs = master_addrs
        self.messenger = Messenger("driver")
        self.messenger.register_service("driver", self)
        self.clients = [YBClient(master_addrs=master_addrs)
                        for _ in range(n_clients)]
        self.table = "usertable"
        self.base_rows = 0
        self._key_seq = _WRITE_KEY_BASE
        self._acked: Dict[int, str] = {}    # key -> acked row token
        self._lat_hist = REGISTRY.entity("server", "driver") \
            .histogram("op_latency_us", "per-op client-side latency")
        self.quit_event = asyncio.Event()

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        return await self.messenger.start(host, port)

    async def shutdown(self):
        for c in self.clients:
            await c.messenger.shutdown()
        await self.messenger.shutdown()

    # --- control RPCs -----------------------------------------------------
    async def rpc_ping(self, payload) -> dict:
        return {"ok": True, "pid": os.getpid(),
                "acked": len(self._acked)}

    async def rpc_tracez(self, payload) -> dict:
        """Client-side span dump: the driver process ROOTS traces (its
        YBClient calls are the sampling edge), so the collector needs
        its dump to stitch complete client->server trees."""
        from ..utils.trace import TRACES
        return TRACES.tracez()

    async def rpc_setup(self, payload) -> dict:
        """Create + load the usertable; returns once every tablet has
        an elected, client-visible leader (the driver-side readiness
        barrier)."""
        rows = int(payload.get("rows", 1000))
        c = self.clients[0]
        info = usertable_info()
        await c.create_table(
            info, num_tablets=int(payload.get("num_tablets", 2)),
            replication_factor=int(payload.get("replication_factor", 1)))
        await self._wait_leaders(timeout=float(payload.get(
            "leader_timeout_s", 30.0)))
        tag = payload.get("tag", "base")
        loaded = 0
        for lo in range(0, rows, 500):
            batch = [_make_row(tag, k)
                     for k in range(lo, min(lo + 500, rows))]
            for attempt in range(20):
                try:
                    await c.insert(self.table, batch)
                    break
                except _TRANSIENT:
                    if attempt == 19:
                        raise
                    await asyncio.sleep(0.1)
                    c._tables.clear()
            loaded += len(batch)
        self.base_rows = rows
        if payload.get("flush", True):
            await self._flush_all()
        ct = await c._table(self.table, refresh=True)
        return {"ok": True, "rows": loaded,
                "table_id": ct.info.table_id}

    async def _wait_leaders(self, timeout: float = 30.0) -> None:
        c = self.clients[0]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                ct = await c._table(self.table, refresh=True)
                if all(l.leader is not None and l.leader_addr() is not None
                       for l in ct.locations):
                    return
            except _TRANSIENT:
                pass
            await asyncio.sleep(0.1)
        raise RpcError(f"no leaders for {self.table}", "TIMED_OUT")

    async def _flush_all(self) -> None:
        c = self.clients[0]
        ct = await c._table(self.table, refresh=True)
        for loc in ct.locations:
            addr = loc.leader_addr()
            if addr is None:
                continue
            try:
                await c.messenger.call(addr, "tserver", "flush",
                                       {"tablet_id": loc.tablet_id},
                                       timeout=30.0)
            except _TRANSIENT:
                pass

    async def rpc_saturation(self, payload) -> dict:
        """Closed-loop probe: `workers` back-to-back op streams for
        `seconds`; the resulting rate is the saturation point the open
        loop doubles."""
        seconds = float(payload.get("seconds", 1.5))
        workers = int(payload.get("workers", 32))
        write_fraction = float(payload.get("write_fraction", 1.0))
        tag = payload.get("tag", "sat")
        rng = np.random.default_rng(int(payload.get("seed", 1)))
        stop_at = time.perf_counter() + seconds
        done = 0

        async def w(i: int):
            nonlocal done
            c = self.clients[i % len(self.clients)]
            while time.perf_counter() < stop_at:
                try:
                    await self._one_op(c, rng, tag, write_fraction,
                                       sla_s=30.0)
                    done += 1
                except _TRANSIENT:
                    await asyncio.sleep(0.01)
        await asyncio.gather(*[w(i) for i in range(workers)])
        return {"ops_per_s": round(done / seconds, 1), "ok": done}

    def _alloc_key(self) -> int:
        self._key_seq += 1
        return self._key_seq

    async def _one_op(self, c: YBClient, rng, tag: str,
                      write_fraction: float, sla_s: float) -> None:
        if rng.random() < write_fraction or self.base_rows == 0:
            k = self._alloc_key()
            token_row = _make_row(tag, k)
            await asyncio.wait_for(c.insert(self.table, [token_row]),
                                   sla_s)
            # acked only on completion: a cancelled op may or may not
            # have landed, and the verifier checks acked ⊆ database
            self._acked[k] = token_row["field0"]
        else:
            k = int(rng.integers(0, self.base_rows))
            await asyncio.wait_for(
                c.get(self.table, {"ycsb_key": k}), sla_s)

    async def rpc_run_phase(self, payload) -> dict:
        """Open loop: `rate` ops/s for `seconds`, each op under an SLA
        deadline of `sla_ms`.  Achieved ops/s counts IN-SLA completions
        only — the goodput an overloaded or convulsing cluster actually
        delivers to clients that still want the answer."""
        rate = float(payload["rate"])
        seconds = float(payload.get("seconds", 2.0))
        sla_s = float(payload.get("sla_ms", 2000)) / 1e3
        write_fraction = float(payload.get("write_fraction", 1.0))
        tag = payload.get("tag", "phase")
        rng = np.random.default_rng(int(payload.get("seed", 2)))
        lat: List[float] = []
        shed = timed_out = conn_err = 0
        tasks = []

        async def one(i: int):
            nonlocal shed, timed_out, conn_err
            c = self.clients[i % len(self.clients)]
            t0 = time.perf_counter()
            try:
                await self._one_op(c, rng, tag, write_fraction, sla_s)
                dt = time.perf_counter() - t0
                lat.append(dt)
                self._lat_hist.increment(dt * 1e6)
            except asyncio.TimeoutError:
                timed_out += 1
            except RpcError as e:
                if e.code == "SERVICE_UNAVAILABLE":
                    shed += 1
                else:
                    conn_err += 1
            except (OSError, RuntimeError):
                conn_err += 1
        total = max(1, int(rate * seconds))
        interval = 1.0 / max(rate, 1e-6)
        t_start = time.perf_counter()
        for i in range(total):
            due = t_start + i * interval
            now = time.perf_counter()
            if now < due:
                await asyncio.sleep(due - now)
            tasks.append(asyncio.ensure_future(one(i)))
        await asyncio.gather(*tasks)
        wall = time.perf_counter() - t_start
        lat_ms = sorted(x * 1e3 for x in lat)

        def pct(q: float) -> float:
            if not lat_ms:
                return 0.0
            return lat_ms[min(len(lat_ms) - 1, int(q * len(lat_ms)))]
        return {"offered_ops_per_s": round(rate, 1),
                "achieved_ops_per_s": round(len(lat) / wall, 1),
                "ok": len(lat), "shed": shed, "timed_out": timed_out,
                "conn_err": conn_err, "sla_ms": sla_s * 1e3,
                "p50_ms": round(pct(0.5), 2),
                "p95_ms": round(pct(0.95), 2),
                "p99_ms": round(pct(0.99), 2),
                "acked_total": len(self._acked)}

    async def rpc_verify(self, payload) -> dict:
        """Quiesced re-read: every acked write must be present with its
        acked bytes (all fields) — the chaos round's zero-data-loss
        assertion.  Per-key bounded retries ride out the last of a
        recovery (both transient ERRORS and not-yet-visible None
        reads); the three failure kinds stay separate so a lagging
        recovery (`unreachable`) can never masquerade as real loss
        (`missing` = a read that SUCCEEDED and found nothing) — a
        zero-loss check asserts all three are zero."""
        sample = payload.get("sample")
        keys = sorted(self._acked)
        if sample and len(keys) > int(sample):
            rng = np.random.default_rng(int(payload.get("seed", 3)))
            keys = sorted(rng.choice(np.asarray(keys), size=int(sample),
                                     replace=False).tolist())
        c = self.clients[0]
        missing: List[int] = []
        mismatched: List[int] = []
        unreachable: List[int] = []
        for k in keys:
            token = self._acked[k]
            row = None
            read_ok = False
            for attempt in range(10):
                read_ok = False
                try:
                    row = await c.get(self.table, {"ycsb_key": k})
                    read_ok = True
                    if row is not None:
                        break
                except _TRANSIENT:
                    c._tables.clear()
                await asyncio.sleep(0.2)
            if row is None:
                (missing if read_ok else unreachable).append(k)
            elif any(row.get(f"field{j}") != token
                     for j in range(_N_FIELDS)):
                mismatched.append(k)
        return {"checked": len(keys), "acked": len(self._acked),
                "missing": len(missing), "mismatched": len(mismatched),
                "unreachable": len(unreachable),
                "missing_examples": missing[:5],
                "mismatched_examples": mismatched[:5],
                "unreachable_examples": unreachable[:5]}

    async def rpc_quit(self, payload) -> dict:
        self.quit_event.set()
        return {"ok": True}


def main(argv=None):
    p = argparse.ArgumentParser(prog="ybtpu-driver")
    p.add_argument("--masters", required=True)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--clients", type=int, default=8)
    args = p.parse_args(argv)
    masters: List[Tuple[str, int]] = []
    for hp in args.masters.split(","):
        if hp:
            h, pt = hp.rsplit(":", 1)
            masters.append((h, int(pt)))

    async def run():
        # the ONE process contract (READY/DRAINED markers, signal
        # set) lives in server_main._serve; the driver only adds its
        # `quit` RPC as an extra stop trigger
        from ..tools.server_main import _serve
        drv = LoadDriver(masters, n_clients=args.clients)
        addr = await drv.start(port=args.port)
        await _serve(addr, drv.shutdown, stop=drv.quit_event)
    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    sys.exit(main())
