"""Cross-process trace assembly + wait-state attribution.

The harness side of the observability layer (CLUSTER.md): every server
process serves ``rpc_tracez`` — a pid+timestamp-stamped dump of its
sampled spans and ASH wait-state histograms.  This module stitches
those dumps into per-trace span TREES (one user write becomes one tree
spanning client, leader and follower processes) and turns per-round
ASH deltas into p99 attribution labels (`cluster_p99_attribution` in
the bench JSON): every round whose p99 exceeds the spread gate gets
its dominant wait state, so a tail spike explains itself instead of
being "flush-pause luck".

Layering: pure data — talks to servers only through a supervisor's
``call`` (duck-typed), never imports server internals.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

#: canonical wait-state -> attribution category.  The bench labels an
#: over-spread round with the CATEGORY (flush/fsync/queue/compile/
#: lock/cpu/scan) so thresholds and dashboards stay stable even as the
#: state table grows.
WAIT_CATEGORIES = {
    "Flush_SstWrite": "flush",
    "Flush_MemtableBackpressure": "flush",
    "WAL_Fsync": "fsync",
    "Catalog_Fsync": "fsync",
    "SchedQueue_Wait": "queue",
    "Raft_Replicate": "queue",
    "Raft_ApplyWait": "queue",
    "SafeTime_Wait": "lock",
    "LeaderLease_Wait": "lock",
    "Lock_Wait": "lock",
    "Device_Compile": "compile",
    "Device_BlockUntilReady": "compile",
    "Compaction_Run": "flush",
    "Bypass_Scan": "scan",
    "OnCpu_Read": "cpu",
    "OnCpu_WriteApply": "cpu",
}


def classify_wait_state(state: str) -> str:
    return WAIT_CATEGORIES.get(state, "other")


async def collect_cluster_tracez(sup, timeout: float = 10.0
                                 ) -> List[dict]:
    """One rpc_tracez dump per ALIVE process in the cluster (tservers,
    masters and drivers all serve the same method on their role
    service).  `sup` is a ClusterSupervisor (duck-typed: ``procs``
    name->proc with ``.role``/``.alive()``, plus ``call``)."""
    dumps: List[dict] = []
    for name, proc in sorted(sup.procs.items()):
        if not proc.alive():
            continue
        service = getattr(proc, "role", "tserver")
        try:
            d = await sup.call(name, service, "tracez", {},
                               timeout=timeout)
        except Exception:   # noqa: BLE001 — a dead/draining process
            continue        # just drops out of the stitch
        d["process"] = name
        dumps.append(d)
    return dumps


def _nodes(dumps: Sequence[dict]) -> List[dict]:
    out = []
    for d in dumps:
        for key in ("spans", "active"):
            for s in d.get(key, ()):
                n = dict(s)
                n["pid"] = d.get("pid")
                n["process"] = d.get("process")
                n["children"] = []
                out.append(n)
    return out


def stitch(dumps: Sequence[dict]) -> Dict[int, dict]:
    """Assemble span trees across process dumps.

    Returns {trace_id: {"roots": [span trees], "span_count": N,
    "pids": [...]}} — a span whose parent is missing from every dump
    (sampled out of the ring, or an unsampled ancestor) becomes a root
    of its own subtree rather than being dropped."""
    nodes = _nodes(dumps)
    by_span: Dict[int, dict] = {}
    for n in nodes:
        # later dumps win on span_id collision (same span active+recent)
        prev = by_span.get(n["span_id"])
        if prev is None or (n.get("finished") and not prev.get("finished")):
            by_span[n["span_id"]] = n
    traces: Dict[int, dict] = {}
    for n in by_span.values():
        t = traces.setdefault(
            n["trace_id"], {"roots": [], "span_count": 0, "pids": set()})
        t["span_count"] += 1
        t["pids"].add(n["pid"])
        parent = by_span.get(n["parent_id"])
        if parent is not None and parent is not n:
            parent["children"].append(n)
        else:
            t["roots"].append(n)
    for t in traces.values():
        t["pids"] = sorted(p for p in t["pids"] if p is not None)
        for r in t["roots"]:
            _sort_tree(r)
    return traces


def _sort_tree(node: dict) -> None:
    node["children"].sort(key=lambda c: c.get("start_unix", 0.0))
    for c in node["children"]:
        _sort_tree(c)


def tree_names(tree: dict) -> List[str]:
    """Flattened span names of one stitched tree (assertion helper)."""
    out = [tree.get("name", "")]
    for c in tree.get("children", ()):
        out.extend(tree_names(c))
    return out


def render_tree(tree: dict, indent: int = 0) -> str:
    """Human-readable one-tree dump (debugging aid)."""
    line = (" " * indent +
            f"{tree.get('name')} [{tree.get('duration_ms')}ms "
            f"pid={tree.get('pid')}]")
    return "\n".join([line] + [render_tree(c, indent + 2)
                               for c in tree.get("children", ())])


# --- ASH attribution -------------------------------------------------------

def merge_ash_cumulative(dumps: Sequence[dict]) -> Dict[str, int]:
    """Sum the monotonic per-state tallies across process dumps (the
    diffable counters — the windowed histograms don't subtract
    cleanly across round boundaries)."""
    out: Dict[str, int] = {}
    for d in dumps:
        for state, n in (d.get("ash", {}) or {}).get(
                "cumulative", {}).items():
            out[state] = out.get(state, 0) + int(n)
    return out


def ash_delta(pre: Dict[str, int], post: Dict[str, int]
              ) -> Dict[str, int]:
    return {s: post.get(s, 0) - pre.get(s, 0)
            for s in post if post.get(s, 0) > pre.get(s, 0)}


def dominant_wait(delta: Dict[str, int],
                  exclude_cpu: bool = True) -> Optional[str]:
    """The wait state that accumulated the most sampler ticks in this
    window.  On-CPU buckets are excluded first (a p99 spike blamed on
    "was running" explains nothing) but win as fallback — on a 2-core
    box pure CPU contention is an honest answer."""
    if not delta:
        return None
    blocked = {s: n for s, n in delta.items()
               if not exclude_cpu or classify_wait_state(s) != "cpu"}
    pool = blocked or delta
    return max(pool.items(), key=lambda kv: kv[1])[0]


def attribute_rounds(rounds: Sequence[dict],
                     spread_gate: float = 3.0) -> dict:
    """Label bench rounds with their dominant wait state.

    ``rounds``: [{"tag", "p99_ms", "wait_delta": {state: ticks}}].
    Every round whose p99 exceeds ``spread_gate`` x the median p99 is
    flagged ``over_spread`` and labeled with its dominant wait state +
    category — the `cluster_p99_attribution` block in the bench JSON.
    """
    p99s = sorted(r.get("p99_ms", 0.0) for r in rounds)
    median = p99s[len(p99s) // 2] if p99s else 0.0
    out_rounds = []
    over = []
    for r in rounds:
        delta = r.get("wait_delta") or {}
        dom = dominant_wait(delta)
        top = sorted(delta.items(), key=lambda kv: -kv[1])[:3]
        is_over = median > 0 and r.get("p99_ms", 0.0) > spread_gate * median
        entry = {
            "tag": r.get("tag"),
            "p99_ms": r.get("p99_ms"),
            "over_spread": is_over,
            "dominant_wait": dom,
            "category": classify_wait_state(dom) if dom else None,
            "top_waits": top,
        }
        out_rounds.append(entry)
        if is_over:
            over.append(entry["tag"])
    return {"spread_gate": spread_gate,
            "median_p99_ms": round(median, 2),
            "over_spread_rounds": over,
            "rounds": out_rounds}
