"""yugabyte_db_tpu — a TPU-native distributed SQL database.

A from-scratch implementation of YugabyteDB's capability surface
(reference: /root/reference, see /root/repo/SURVEY.md), re-architected
TPU-first:

- Control plane (Raft consensus, WAL, tablet lifecycle, master/catalog,
  RPC) is host-side code with the same seams as the reference
  (`src/yb/consensus/`, `src/yb/master/`, `src/yb/rpc/`).
- Data-plane hot loops — scan/filter/aggregate execution (reference:
  `src/yb/docdb/pgsql_operation.cc:2790` ExecuteScalar) and LSM
  compaction merge + MVCC GC (reference:
  `src/yb/rocksdb/db/compaction_job.cc:665`,
  `src/yb/docdb/docdb_compaction_context.cc:783`) — run as JAX/XLA
  kernels on TPU, behind a runtime flag (`tpu_pushdown_enabled`).
- Storage blocks are columnar from day one so device decode is a
  reinterpret + reshape, not a row loop.

Package layout:
  utils/      Status/Result, hybrid time (HLC), flags, metrics, trace
  dockv/      doc key / value encoding, packed rows, partitions
  storage/    LSM: memtable, SSTables (columnar blocks), merge, compaction
  docdb/      MVCC document store: read/write paths, intents, conflicts
  ops/        JAX kernels: scan/filter/aggregate, compaction merge, vector
  parallel/   device mesh, shard_map distributed scan, psum combine
  consensus/  per-tablet Raft + replicated log (the WAL)
  tablet/     tablet core, peers, operations, bootstrap, snapshots, txns
  tserver/    data node: tablet service, read path driver, heartbeater
  master/     control plane: sys catalog, catalog manager, load balancer
  client/     cluster client: meta cache, batcher, transactions
  rpc/        async RPC framework (asyncio reactors, binary framing)
  ql/         query layers: YSQL-subset SQL, YCQL, Redis
  models/     end-to-end engine pipelines (benchmark workloads, flagship
              scan models used by __graft_entry__)
  tools/      admin CLI, local cluster launcher
"""

__version__ = "0.1.0"

# Hybrid times and key hashes are 64-bit; JAX must carry u64 end-to-end.
# (TPU emulates 64-bit integer ops; the scan kernels only use them for
# visibility compares, which are negligible next to the f32 aggregate work.)
import os as _os  # noqa: E402

import jax as _jax  # noqa: E402

_jax.config.update("jax_enable_x64", True)

# Operator platform override: the deployment environment may preset a
# platform (e.g. a TPU tunnel) via JAX_PLATFORMS before process start;
# YBTPU_PLATFORM lets servers/tools force e.g. cpu regardless.
if _os.environ.get("YBTPU_PLATFORM"):
    _jax.config.update("jax_platforms", _os.environ["YBTPU_PLATFORM"])

# Persistent XLA compilation cache: TPU sort/scan kernels are expensive to
# compile (tens of seconds over the tunnel); cache them across processes.
# Namespaced by host fingerprint — repo snapshots move between machines,
# and code compiled for another CPU's feature set can SIGILL (hostfp.py).
# CPU backends skip the cache entirely: their compiles are fast, and
# XLA:CPU AOT entries embed tuning pseudo-features (prefer-no-gather
# etc.) that fail the loader's machine check even on the same host —
# the r03 bench-tail warning class.
from .hostfp import host_fingerprint as _host_fp  # noqa: E402

_platform_env = (_os.environ.get("YBTPU_PLATFORM")
                 or _os.environ.get("JAX_PLATFORMS", ""))
if _platform_env:
    _accel_likely = "cpu" not in _platform_env.lower()
else:
    # no explicit platform: probe device nodes instead of initializing
    # a backend here (jax.default_backend() could hang on a wedged
    # tunnel); no accelerator nodes -> CPU backend -> no cache
    import glob as _glob
    _accel_likely = bool(_glob.glob("/dev/accel*")
                         or _glob.glob("/dev/nvidia*"))
if _accel_likely:
    _cache_dir = _os.environ.get(
        "YBTPU_COMPILE_CACHE",
        _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
            ".jax_cache", _host_fp()))
    try:
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # older jax without the knob — fine, just slower
        pass
