"""MiniCluster: a real master + N real tservers inside one process.

The reference's test backbone (reference:
src/yb/integration-tests/mini_cluster.h:121): no simulated backend —
the same Raft/LSM/RPC stack on localhost ports. Used by integration
tests and the local dev CLI.
"""
from __future__ import annotations

import asyncio
import os
from typing import List, Optional

from ..client import YBClient
from ..master import Master
from ..tserver import TabletServer


class MiniCluster:
    def __init__(self, root: str, num_tservers: int = 3,
                 num_masters: int = 1,
                 zones: Optional[List[str]] = None):
        """zones: per-tserver zone labels (index-aligned, cycled when
        shorter) for geo-placement tests."""
        self.root = root
        self.num_tservers = num_tservers
        self.num_masters = num_masters
        self.zones = zones
        self.masters: List[Master] = []
        self.tservers: List[TabletServer] = []

    @property
    def master(self) -> Master:
        """The leader master (falls back to the first)."""
        for m in self.masters:
            if m.is_leader():
                return m
        return self.masters[0]

    def master_addrs(self):
        return [m.messenger.addr for m in self.masters]

    async def start(self) -> "MiniCluster":
        if os.environ.get("YBTPU_LOOP_MONITOR") == "1":
            # blocked-event-loop detector (utils/sanitizer.py): logs
            # any callback stalling the loop past the threshold
            from ..utils.sanitizer import enable_loop_monitor
            enable_loop_monitor()
        for i in range(self.num_masters):
            m = Master(os.path.join(self.root, f"master-{i}"), uuid=f"m{i}")
            await m.start()
            self.masters.append(m)
        if self.num_masters > 1:
            peers = [(m.uuid, m.messenger.addr) for m in self.masters]
            for m in self.masters:
                await m.start_consensus(peers)
            # wait for a leader master
            t0 = asyncio.get_event_loop().time()
            while asyncio.get_event_loop().time() - t0 < 10.0:
                if any(m.is_leader() and m.consensus is not None
                       and m.consensus.is_leader() for m in self.masters):
                    break
                await asyncio.sleep(0.05)
        maddrs = self.master_addrs()
        for i in range(self.num_tservers):
            zone = (self.zones[i % len(self.zones)] if self.zones
                    else "zone-default")
            ts = TabletServer(f"ts-{i}", os.path.join(self.root, f"ts-{i}"),
                              master_addrs=maddrs, zone=zone)
            await ts.start()
            self.tservers.append(ts)
        await self.wait_for_tservers()
        return self

    async def stop_master(self, idx: int):
        m = self.masters[idx]
        if m.consensus is not None:
            await m.consensus.shutdown()
        await m.shutdown()

    async def wait_for_tservers(self, timeout: float = 10.0):
        t0 = asyncio.get_event_loop().time()
        while asyncio.get_event_loop().time() - t0 < timeout:
            for ts in self.tservers:
                await ts._heartbeat_once()
            if len(self.master.live_tservers()) >= self.num_tservers:
                return
            await asyncio.sleep(0.05)
        raise TimeoutError("tservers did not register")

    def client(self) -> YBClient:
        return YBClient(master_addrs=self.master_addrs())

    async def restart_tserver(self, idx: int):
        ts = self.tservers[idx]
        old_addr = ts.messenger.addr
        await ts.shutdown()
        new = TabletServer(ts.uuid, ts.fs_root,
                           master_addrs=self.master_addrs())
        # rebind the SAME endpoint: Raft peer configs and client meta
        # caches address this node by host:port, exactly like a real
        # deployment restarting in place
        try:
            await new.start(host=old_addr[0], port=old_addr[1])
        except OSError:
            await new.start()        # port raced away: fresh bind
        self.tservers[idx] = new
        return new

    async def stop_tserver(self, idx: int):
        await self.tservers[idx].shutdown()

    async def wait_for_leaders(self, table: str, timeout: float = 15.0):
        """Wait until every tablet of `table` has an elected leader
        reported to the master."""
        c = self.client()
        t0 = asyncio.get_event_loop().time()
        while asyncio.get_event_loop().time() - t0 < timeout:
            for ts in self.tservers:
                try:
                    await ts._heartbeat_once()
                except Exception:
                    pass
            try:
                ct = await c._table(table, refresh=True)
                if all(l.leader is not None and l.leader_addr() is not None
                       for l in ct.locations):
                    await c.messenger.shutdown()
                    return
            except Exception:
                pass
            await asyncio.sleep(0.05)
        await c.messenger.shutdown()
        raise TimeoutError(f"no leaders for {table}")

    async def shutdown(self):
        # sanitizer sweep (reference: TSAN/DCHECK builds): every test
        # drive doubles as a state-invariant check — claims vs intents,
        # read-lock symmetry, memtable probe guards, manifest/file
        # consistency.  Violations are collected BEFORE teardown but
        # raised AFTER it: servers must not leak into later tests, and
        # the raise must not happen mid-finally where it would mask a
        # test's own exception during teardown.
        violations = []
        if os.environ.get("YBTPU_SANITIZE") == "1":
            from ..utils import sanitizer
            violations = sanitizer.check_cluster(self)
        for ts in self.tservers:
            await ts.shutdown()
        for m in self.masters:
            if m.consensus is not None:
                await m.consensus.shutdown()
            await m.shutdown()
        if violations:
            raise AssertionError(
                "sanitizer violations at cluster shutdown:\n  "
                + "\n  ".join(violations))
