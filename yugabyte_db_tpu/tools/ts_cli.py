"""yb-ts-cli analog: per-TABLET-SERVER operations addressed directly at
one tserver's RPC endpoint (reference: src/yb/tools/ts-cli.cc — the ops
surface an operator points at a single node, no master involved).

    python -m yugabyte_db_tpu.tools.ts_cli --server HOST:PORT <cmd> ...

Commands:
    status                      server uuid + per-tablet role/size/ssts
    list_tablets                tablet ids with leadership
    tablet_status TABLET_ID     one tablet's replica state
    flush_tablet TABLET_ID      flush its memtable to an SST
    compact_tablet TABLET_ID    major-compact it
    mem_trackers                memory accounting rollup
    server_clock                current hybrid time
    set_flag NAME VALUE         hot-update a runtime flag on this server
    list_flags                  all flag values on this server
    leader_stepdown TABLET_ID   ask the replica to step down
"""
from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ..rpc.messenger import Messenger, RpcError

_MIN_ARGS = {"tablet_status": 1, "flush_tablet": 1, "compact_tablet": 1,
             "set_flag": 2, "leader_stepdown": 1}

_RPC_OF = {
    "status": "status",
    "tablet_status": "tablet_status",
    "flush_tablet": "flush",
    "compact_tablet": "compact",
    "mem_trackers": "mem_trackers",
    "server_clock": "server_clock",
    "set_flag": "set_flag",
    "list_flags": "list_flags",
    "leader_stepdown": "leader_stepdown",
}


async def run_command(args) -> int:
    host, port = args.server.rsplit(":", 1)
    addr = (host, int(port))
    m = Messenger("ts-cli")
    await m.start()
    try:
        cmd, pos = args.command, args.args
        if len(pos) < _MIN_ARGS.get(cmd, 0):
            print(f"{cmd}: needs {_MIN_ARGS[cmd]} argument(s)",
                  file=sys.stderr)
            return 2
        if cmd == "list_tablets":
            r = await m.call(addr, "tserver", "status", {}, timeout=10.0)
            out = [{"tablet_id": tid, **info}
                   for tid, info in sorted(r["tablets"].items())]
        elif cmd in ("tablet_status", "flush_tablet", "compact_tablet",
                     "leader_stepdown"):
            r = await m.call(addr, "tserver", _RPC_OF[cmd],
                             {"tablet_id": pos[0]}, timeout=300.0)
            out = r
        elif cmd == "set_flag":
            out = await m.call(addr, "tserver", "set_flag",
                               {"name": pos[0], "value": pos[1]},
                               timeout=10.0)
        elif cmd in _RPC_OF:
            out = await m.call(addr, "tserver", _RPC_OF[cmd], {},
                               timeout=30.0)
        else:
            print(f"unknown command {cmd}", file=sys.stderr)
            return 2
        print(json.dumps(out, indent=1, default=str))
        return 0
    except (RpcError, OSError, asyncio.TimeoutError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await m.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ts_cli", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--server", required=True,
                    help="tserver RPC endpoint HOST:PORT")
    ap.add_argument("command")
    ap.add_argument("args", nargs="*")
    return asyncio.run(run_command(ap.parse_args(argv)))


if __name__ == "__main__":
    sys.exit(main())
