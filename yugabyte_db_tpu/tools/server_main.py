"""Standalone server entry points (real processes).

    python -m yugabyte_db_tpu.tools.server_main master \
        --fs-root DIR --port P
    python -m yugabyte_db_tpu.tools.server_main tserver \
        --uuid ts-0 --fs-root DIR --port P --masters host:port[,host:port]

The process analog of yb-master/yb-tserver binaries (reference:
src/yb/master/master_main.cc, tserver/tablet_server_main.cc); used by
the ExternalMiniCluster test harness for crash/restart fidelity
(reference: integration-tests/external_mini_cluster.h).
"""
from __future__ import annotations

import argparse
import asyncio
import sys


async def run_master(args):
    from ..master import Master
    m = Master(args.fs_root)
    addr = await m.start(port=args.port)
    print(f"READY {addr[0]}:{addr[1]}", flush=True)
    while True:
        await asyncio.sleep(3600)


async def run_tserver(args):
    from ..tserver import TabletServer
    masters = []
    for hp in args.masters.split(","):
        h, p = hp.rsplit(":", 1)
        masters.append((h, int(p)))
    ts = TabletServer(args.uuid, args.fs_root, master_addrs=masters)
    addr = await ts.start(port=args.port)
    print(f"READY {addr[0]}:{addr[1]}", flush=True)
    while True:
        await asyncio.sleep(3600)


def main(argv=None):
    p = argparse.ArgumentParser(prog="ybtpu-server")
    p.add_argument("role", choices=["master", "tserver"])
    p.add_argument("--fs-root", required=True)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--uuid", default="ts-0")
    p.add_argument("--masters", default="")
    args = p.parse_args(argv)
    try:
        asyncio.run(run_master(args) if args.role == "master"
                    else run_tserver(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    sys.exit(main())
