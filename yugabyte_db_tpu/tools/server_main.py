"""Standalone server entry points (real processes).

    python -m yugabyte_db_tpu.tools.server_main master \
        --fs-root DIR --port P [--uuid m0] [--auto-balance]
    python -m yugabyte_db_tpu.tools.server_main tserver \
        --uuid ts-0 --fs-root DIR --port P --masters host:port[,host:port] \
        [--zone z]

The process analog of yb-master/yb-tserver binaries (reference:
src/yb/master/master_main.cc, tserver/tablet_server_main.cc); spawned
by the multi-process cluster supervisor (cluster/supervisor.py) and by
the ExternalMiniCluster-style tests for crash/restart fidelity.

Process contract (CLUSTER.md):

- the first stdout line once serving is ``READY <host>:<port>`` —
  supervisors redirect stdout to the process log file and poll it;
- SIGTERM = graceful drain (tserver: release bypass SST leases, flush
  memtables, close WALs; master: stop loops, persist nothing extra —
  the catalog is already durable per commit), then exit 0.  SIGKILL =
  crash: nothing runs, restart takes the recovery path;
- env handshake read BEFORE serving: ``YBTPU_CRASH_POINTS`` (comma
  list) arms crash points, ``YBTPU_CRASH_HARD=1`` makes them kill the
  process for real, ``YBTPU_FLAGS`` (``name=value,...``) presets
  runtime flags — so faults/flags can cover even the first request.
"""
from __future__ import annotations

import argparse
import asyncio
import signal
import sys


def _apply_env_handshake():
    import os

    from ..utils import fault_injection, flags
    fault_injection.arm_from_env()
    spec = os.environ.get("YBTPU_FLAGS", "")
    for item in spec.split(","):
        item = item.strip()
        if not item or "=" not in item:
            continue
        name, value = item.split("=", 1)
        flags.coerce_and_set(name, value)   # unknown flag -> loud crash


async def _serve(addr, drain, stop=None) -> None:
    """The supervisor's process contract (CLUSTER.md), in ONE place
    for every child role: READY line + wait for SIGTERM/SIGINT (or an
    externally-set `stop` event — the driver's `quit` RPC), then the
    graceful drain and the DRAINED marker.  A supervisor that wants
    crash semantics sends SIGKILL instead and none of this runs."""
    stop = stop if stop is not None else asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    print(f"READY {addr[0]}:{addr[1]}", flush=True)
    await stop.wait()
    await drain()
    print("DRAINED", flush=True)


def _start_ash_sampler():
    """Background ASH wait-state sampler (utils/trace.AshSampler): one
    daemon thread per server process, ticking every
    ``ash_sample_interval_ms`` — what rpc_tracez's histograms and the
    bench's p99 attribution read."""
    from ..utils.trace import ASH
    ASH.start()
    return ASH


async def run_master(args):
    from ..master import Master
    _apply_env_handshake()
    ash = _start_ash_sampler()
    m = Master(args.fs_root, uuid=args.uuid or "m0")
    addr = await m.start(port=args.port, auto_balance=args.auto_balance)

    async def drain():
        await m.shutdown()
        ash.stop()
    await _serve(addr, drain)


async def run_tserver(args):
    from ..tserver import TabletServer
    _apply_env_handshake()
    ash = _start_ash_sampler()
    masters = []
    for hp in args.masters.split(","):
        if not hp:
            continue
        h, p = hp.rsplit(":", 1)
        masters.append((h, int(p)))
    ts = TabletServer(args.uuid or "ts-0", args.fs_root,
                      master_addrs=masters, zone=args.zone)
    addr = await ts.start(port=args.port)

    async def drain():
        await ts.shutdown(graceful=True)
        ash.stop()
    await _serve(addr, drain)


def main(argv=None):
    p = argparse.ArgumentParser(prog="ybtpu-server")
    p.add_argument("role", choices=["master", "tserver"])
    p.add_argument("--fs-root", required=True)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--uuid", default=None)
    p.add_argument("--masters", default="")
    p.add_argument("--zone", default="zone-default")
    p.add_argument("--auto-balance", action="store_true",
                   help="master only: run load-balancer ticks in the "
                        "maintenance loop")
    args = p.parse_args(argv)
    try:
        asyncio.run(run_master(args) if args.role == "master"
                    else run_tserver(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    sys.exit(main())
