"""yugabyted-style single-command cluster launcher + SQL shell.

Reference: bin/yugabyted (start/stop node, join cluster, UI). Runs a
master + N tservers + CQL/Redis wire servers in one process and drops
into an interactive SQL shell (ysqlsh analog).

    python -m yugabyte_db_tpu.tools.ybtpud --data-dir /tmp/yb --tservers 3
"""
from __future__ import annotations

import argparse
import asyncio
import sys

from ..master import Master
from ..ql import SqlSession
from ..ql.cql_server import CqlServer
from ..ql.connection_manager import PooledPgServer
from ..ql.redis_server import RedisServer
from ..tserver import TabletServer
from ..tserver.webserver import StatusWebServer


def _load_ports(data_dir: str) -> dict:
    """Persisted server ports: Raft configs and catalog locations
    address nodes by host:port, so a relaunch must rebind the SAME
    endpoints (reference: yugabyted persists its server conf). First
    start records the OS-assigned ports; later starts reuse them."""
    import json
    import os
    path = os.path.join(data_dir, "ports.json")
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    return {}


def _save_ports(data_dir: str, ports: dict) -> None:
    import json
    import os
    os.makedirs(data_dir, exist_ok=True)
    with open(os.path.join(data_dir, "ports.json"), "w") as f:
        json.dump(ports, f)


async def serve(args):
    # background ASH wait-state sampler (same as server_main): the
    # /ash endpoint and rpc_tracez histograms are live from the first
    # request in the all-in-one dev server too
    from ..utils.trace import ASH
    ASH.start()
    ports = _load_ports(args.data_dir)
    master = Master(f"{args.data_dir}/master")
    maddr = await master.start(
        port=args.master_port or ports.get("master", 0),
        auto_balance=args.auto_balance)
    ports["master"] = maddr[1]
    print(f"master        : {maddr[0]}:{maddr[1]}")
    tservers = []
    for i in range(args.tservers):
        ts = TabletServer(f"ts-{i}", f"{args.data_dir}/ts-{i}",
                          master_addrs=[maddr])
        want = (args.tserver_port + i if args.tserver_port
                else ports.get(f"ts-{i}", 0))
        addr = await ts.start(port=want)
        ports[f"ts-{i}"] = addr[1]
        tservers.append(ts)
        print(f"tserver ts-{i}  : {addr[0]}:{addr[1]}")
    _save_ports(args.data_dir, ports)
    def scheduler_handler():
        # per-tserver request-scheduler lanes: depth/shed/wait/batch —
        # the dashboard's scheduler panel and ops curl this
        import json as _json
        return _json.dumps(
            {ts.uuid: {"enabled": ts.scheduler.enabled(),
                       "lanes": ts.scheduler.stats()}
             for ts in tservers}, indent=1), "application/json"

    web = StatusWebServer("ybtpu", extra_handlers={
        **master.web_handlers(), "/scheduler": scheduler_handler})
    waddr = await web.start(port=args.web_port)
    print(f"status ui     : http://{waddr[0]}:{waddr[1]}/metrics "
          f"(/tables /tablet-servers /tablets /scheduler /rpcz /ash)")

    from ..client import YBClient
    client = YBClient(maddr)
    # the connection manager IS the front door (reference: YSQL
    # Connection Manager/odyssey fronting the PG backends)
    pg = PooledPgServer(YBClient(maddr), pool_size=args.pg_pool_size)
    paddr = await pg.start()
    print(f"ysql (pg wire): {paddr[0]}:{paddr[1]} "
          f"(pooled, {args.pg_pool_size} sessions)")
    cql = CqlServer(client)
    caddr = await cql.start()
    print(f"ycql          : {caddr[0]}:{caddr[1]}")
    redis = RedisServer(YBClient(maddr))
    raddr = await redis.start()
    print(f"yedis         : {raddr[0]}:{raddr[1]}")

    # wait for tserver registration
    for _ in range(100):
        for ts in tservers:
            await ts._heartbeat_once()
        if len(master.live_tservers()) >= args.tservers:
            break
        await asyncio.sleep(0.05)

    if args.shell:
        await sql_shell(SqlSession(client))
        for ts in tservers:
            await ts.shutdown()
        await master.shutdown()
    else:
        print("ready. Ctrl-C to stop.")
        try:
            while True:
                await asyncio.sleep(3600)
        except (KeyboardInterrupt, asyncio.CancelledError):
            pass


async def sql_shell(session: SqlSession):
    print("ybtpu SQL shell — end statements with ';', \\q to quit")
    loop = asyncio.get_running_loop()
    buf = ""
    while True:
        prompt = "ybtpu=# " if not buf else "ybtpu-# "
        try:
            line = await loop.run_in_executor(None, input, prompt)
        except (EOFError, KeyboardInterrupt):
            break
        if line.strip() in ("\\q", "exit", "quit"):
            break
        buf += " " + line
        if ";" not in line:
            continue
        sql, buf = buf.strip(), ""
        try:
            res = await session.execute(sql.rstrip(";"))
            if res.rows:
                cols = list(res.rows[0].keys())
                print(" | ".join(cols))
                print("-+-".join("-" * len(c) for c in cols))
                for r in res.rows:
                    print(" | ".join(str(r.get(c)) for c in cols))
                print(f"({len(res.rows)} rows)")
            else:
                print(res.status)
        except Exception as e:   # noqa: BLE001 — REPL surfaces all errors
            print(f"ERROR: {e}")


def main(argv=None):
    p = argparse.ArgumentParser(prog="ybtpud")
    p.add_argument("--data-dir", default="/tmp/ybtpu-data")
    p.add_argument("--tservers", type=int, default=1)
    p.add_argument("--master-port", type=int, default=0)
    p.add_argument("--tserver-port", type=int, default=0)
    p.add_argument("--web-port", type=int, default=0)
    p.add_argument("--auto-balance", action="store_true")
    p.add_argument("--pg-pool-size", type=int, default=16,
                   help="connection-manager backend session pool size")
    p.add_argument("--shell", action="store_true", default=True)
    p.add_argument("--no-shell", dest="shell", action="store_false")
    args = p.parse_args(argv)
    asyncio.run(serve(args))


if __name__ == "__main__":
    sys.exit(main())
