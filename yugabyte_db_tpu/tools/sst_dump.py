"""SST inspection tool (sst_dump/ldb analog; reference:
src/yb/tools/sst_dump.cc, ldb.cc).

    python -m yugabyte_db_tpu.tools.sst_dump FILE [--blocks] [--entries N]
    python -m yugabyte_db_tpu.tools.sst_dump --wal DIR [--entries N]
"""
from __future__ import annotations

import argparse
import sys


def dump_sst(path: str, show_blocks: bool, n_entries: int):
    from ..storage.sst import SstReader
    from ..dockv.key_encoding import SubDocKey
    r = SstReader(path)
    print(f"{path}:")
    print(f"  entries:   {r.num_entries}")
    print(f"  blocks:    {r.num_blocks()}")
    print(f"  file size: {r.file_size}")
    print(f"  min key:   {r.min_key.hex()}")
    print(f"  max key:   {r.max_key.hex()}")
    print(f"  frontier:  {r.frontier}")
    if show_blocks:
        for i, e in enumerate(r.index):
            kind = "columnar-only" if e.length == 0 else "row"
            sidecar = "+sidecar" if e.col_offset >= 0 else ""
            print(f"  block {i}: {e.num_rows} rows, {kind}{sidecar}, "
                  f"[{e.first_key.hex()[:24]}.. {e.last_key.hex()[:24]}..]")
    if n_entries:
        shown = 0
        for k, v in r.iterate():
            try:
                sdk = SubDocKey.decode(k)
                desc = (f"pk={[e.value for e in sdk.doc_key.hashed + sdk.doc_key.range]} "
                        f"ht={sdk.doc_ht}")
            except Exception:
                desc = k.hex()[:48]
            print(f"    {desc}  value[{len(v)}B] kind={v[0]:#x}")
            shown += 1
            if shown >= n_entries:
                break


def dump_wal(directory: str, n_entries: int):
    from ..consensus.log import Log
    log = Log(directory, fsync=False)
    print(f"{directory}: entries {log._first_index}..{log.last_index}")
    for e in log.all_entries()[:n_entries or 20]:
        print(f"  [{e.term}:{e.index}] {e.etype} payload[{len(e.payload)}B]")
    log.close()


def main(argv=None):
    p = argparse.ArgumentParser(prog="ybtpu-sst-dump")
    p.add_argument("path", nargs="?")
    p.add_argument("--wal", help="dump a WAL directory instead")
    p.add_argument("--blocks", action="store_true")
    p.add_argument("--entries", type=int, default=0)
    args = p.parse_args(argv)
    if args.wal:
        dump_wal(args.wal, args.entries)
    elif args.path:
        dump_sst(args.path, args.blocks, args.entries)
    else:
        p.error("need an SST path or --wal DIR")
    return 0


if __name__ == "__main__":
    sys.exit(main())
