"""yb-admin-style cluster admin CLI.

Reference: src/yb/tools/yb-admin_cli.cc — snapshot/restore, tablet moves,
compactions, tserver listing. Usage:

    python -m yugabyte_db_tpu.tools.ybtpu_admin --master HOST:PORT <cmd> ...

Commands: list_tables, list_tservers, list_tablets TABLE,
create_snapshot TABLE, restore_snapshot SNAPSHOT_ID NEW_TABLE,
create_snapshot_schedule TABLE INTERVAL_S KEEP,
list_snapshot_schedules TABLE,
restore_snapshot_schedule SCHEDULE_ID AT_UNIX_TS NEW_TABLE,
setup_xcluster SOURCE_HOST:PORT TABLE, drop_xcluster TABLE,
list_xcluster,
split_tablet TABLET_ID, move_replica TABLET_ID FROM TO, balance_tick,
blacklist TS_UUID, compact_table TABLE, flush_table TABLE,
create_tablespace NAME ZONE:MIN[,ZONE:MIN...] [PREF[,PREF...]],
set_placement_info ZONE:MIN[,...] [PREF[,...]], list_tablespaces,
drop_tablespace NAME
"""
from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ..client import YBClient
from ..docdb.wire import read_request_to_wire


# minimum positional args per command (commands absent here take 0)
_MIN_ARGS = {
    "list_tablets": 1, "create_snapshot": 1, "restore_snapshot": 2,
    "create_snapshot_schedule": 3, "restore_snapshot_schedule": 3,
    "split_tablet": 1, "move_replica": 3, "blacklist": 1,
    "setup_xcluster": 2, "drop_xcluster": 1,
    "compact_table": 1, "flush_table": 1,
    "create_tablespace": 2, "set_placement_info": 1,
    "drop_tablespace": 1,
}


async def run_command(args) -> int:
    host, port = args.master.rsplit(":", 1)
    client = YBClient((host, int(port)))
    m = client.messenger
    maddr = client.master_addr
    cmd = args.command
    a = args.args
    if len(a) < _MIN_ARGS.get(cmd, 0):
        print(f"error: {cmd} takes at least {_MIN_ARGS[cmd]} argument(s) "
              f"(see module docstring)", file=sys.stderr)
        return 1
    if cmd == "list_tables":
        print(json.dumps(await client.list_tables(), indent=1))
    elif cmd == "list_tservers":
        r = await m.call(maddr, "master", "list_tservers", {})
        print(json.dumps(r, indent=1))
    elif cmd == "list_tablets":
        ct = await client._table(a[0])
        for l in ct.locations:
            print(l.tablet_id, l.partition, "leader:", l.leader,
                  "replicas:", [u for u, _ in l.replicas])
    elif cmd == "create_snapshot":
        r = await m.call(maddr, "master", "create_snapshot",
                         {"table": a[0]}, timeout=120.0)
        print(json.dumps(r))
    elif cmd == "restore_snapshot":
        r = await m.call(maddr, "master", "restore_snapshot",
                         {"snapshot_id": a[0], "new_name": a[1]},
                         timeout=120.0)
        print(json.dumps(r))
    elif cmd == "create_snapshot_schedule":
        r = await m.call(maddr, "master", "create_snapshot_schedule",
                         {"table": a[0], "interval_s": float(a[1]),
                          "keep": int(a[2])}, timeout=120.0)
        print(json.dumps(r))
    elif cmd == "list_snapshot_schedules":
        r = await m.call(maddr, "master", "list_snapshot_schedules",
                         {"table": a[0]} if a else {}, timeout=120.0)
        print(json.dumps(r, indent=1))
    elif cmd == "restore_snapshot_schedule":
        r = await m.call(maddr, "master", "restore_snapshot_schedule",
                         {"schedule_id": a[0], "at": float(a[1]),
                          "new_name": a[2]}, timeout=120.0)
        print(json.dumps(r))
    elif cmd == "setup_xcluster":
        if ":" not in a[0] or not a[0].rsplit(":", 1)[1].isdigit():
            print(f"error: setup_xcluster needs SOURCE_HOST:PORT, "
                  f"got {a[0]!r}", file=sys.stderr)
            return 1
        shost, sport = a[0].rsplit(":", 1)
        r = await m.call(maddr, "master", "setup_xcluster_replication",
                         {"source_master": [shost, int(sport)],
                          "table": a[1]}, timeout=120.0)
        print(json.dumps(r))
    elif cmd == "drop_xcluster":
        r = await m.call(maddr, "master", "drop_xcluster_replication",
                         {"table": a[0]}, timeout=120.0)
        print(json.dumps(r))
    elif cmd == "list_xcluster":
        r = await m.call(maddr, "master", "list_xcluster_replication",
                         {}, timeout=120.0)
        print(json.dumps(r, indent=1))
    elif cmd == "split_tablet":
        r = await m.call(maddr, "master", "split_tablet",
                         {"tablet_id": a[0]}, timeout=120.0)
        print(json.dumps(r))
    elif cmd == "move_replica":
        r = await m.call(maddr, "master", "move_replica",
                         {"tablet_id": a[0], "from": a[1], "to": a[2]},
                         timeout=120.0)
        print(json.dumps(r))
    elif cmd == "balance_tick":
        r = await m.call(maddr, "master", "balance_tick", {}, timeout=120.0)
        print(json.dumps(r))
    elif cmd == "blacklist":
        r = await m.call(maddr, "master", "blacklist", {"ts_uuid": a[0]})
        print(json.dumps(r))
    elif cmd in ("create_tablespace", "set_placement_info"):
        # args: [NAME] ZONE:MIN[,ZONE:MIN...] [PREF_ZONE[,PREF_ZONE...]]
        pos = 0 if cmd == "set_placement_info" else 1
        placement = [{"zone": z, "min_replicas": int(n)}
                     for z, n in (b.split(":") for b in
                                  a[pos].split(",") if b)]
        pref = a[pos + 1].split(",") if len(a) > pos + 1 else []
        payload = {"placement": placement, "preferred_zones": pref}
        if cmd == "create_tablespace":
            payload["name"] = a[0]
        r = await m.call(maddr, "master", cmd, payload, timeout=30.0)
        print(json.dumps(r))
    elif cmd == "list_tablespaces":
        r = await m.call(maddr, "master", "list_tablespaces", {},
                         timeout=30.0)
        print(json.dumps(r, indent=1))
    elif cmd == "drop_tablespace":
        r = await m.call(maddr, "master", "drop_tablespace",
                         {"name": a[0]}, timeout=30.0)
        print(json.dumps(r))
    elif cmd in ("compact_table", "flush_table"):
        method = "compact" if cmd == "compact_table" else "flush"
        ct = await client._table(a[0])
        for l in ct.locations:
            r = await client._call_leader(ct, l.tablet_id, method,
                                          {"tablet_id": l.tablet_id})
            print(l.tablet_id, r)
    else:
        print(f"unknown command {cmd}", file=sys.stderr)
        return 1
    await m.shutdown()
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(prog="ybtpu-admin")
    p.add_argument("--master", required=True, help="master host:port")
    p.add_argument("command")
    p.add_argument("args", nargs="*")
    args = p.parse_args(argv)
    from ..rpc.messenger import RpcError
    try:
        return asyncio.run(run_command(args))
    except RpcError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        return 0   # output piped into a closed reader (e.g. | head)


if __name__ == "__main__":
    sys.exit(main())
