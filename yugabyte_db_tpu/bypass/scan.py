"""Keyless v2 SST-direct scan engine.

Opens a pinned snapshot's SST files directly (fresh readers over the
leased paths — never the store's own reader list, and with NO
key_builder bound, so a key-matrix rebuild is structurally impossible:
there is no thunk to fire) and streams their columnar blocks through
the shared pow2-bucket chunk pipeline (ops/stream_scan.py).  The v2
format's promise finally cashes out here: eligibility, zone-map
pruning, chunk-safety and SST-run ordering all read only the stored
boundary keys (k0/k1), so an all-v2 tablet scans end-to-end with ZERO
key-matrix rebuilds (``KEY_REBUILD_STATS`` asserts it in tests).

Eligibility is typed (errors.py): anything the engine cannot serve
exactly — hash groups, varlen-only columns, non-chunk-safe block
sequences, kernel-incompatible expressions — raises BypassIneligible
and the caller falls back to the RPC path.  What IS served is
byte-identical to the RPC scan path at the same read point: the same
zone-prune gate, the same chunk plan and shared bucket, the same
kernel and combine rules, and the same monolithic twin under
``min_chunks`` (the near-data prefilter preserves this bit-for-bit —
see bypass/prefilter.py).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..ops.device_batch import bucket_rows, build_batch
from ..ops.grouped_scan import DictGroupSpec
from ..ops.scan import AggSpec, HashGroupSpec, ScanKernel, _expand_avg
from ..ops.stream_scan import (LAST_STREAM_STATS, chunk_safe_mvcc,
                               streaming_scan_aggregate)
from ..storage.columnar import KEY_REBUILD_STATS, ColumnarBlock
from ..storage.sst import SstReader
from ..utils import flags
from .errors import (REASON_COLUMN_NOT_FIXED, REASON_DOC_OFF,
                     REASON_DOC_SHAPE, REASON_EXPR_SHAPE,
                     REASON_GROUPED_OFF, REASON_HASH_GROUP,
                     REASON_JOIN_OFF, REASON_JOIN_SHAPE,
                     REASON_NO_COLUMNAR, REASON_NOT_AGGREGATE,
                     REASON_NOT_CHUNK_SAFE, REASON_SLOT_OVERFLOW,
                     BypassIneligible)
from .prefilter import make_prefilter


def open_snapshot_readers(snap) -> List[SstReader]:
    """Fresh SstReaders over a snapshot's leased paths.  No row_decoder
    and — deliberately — no key_builder: the keyless scanner has no
    lazy-rebuild path to fall into."""
    return [SstReader(p, row_decoder=None, key_builder=None)
            for p in snap.sst_paths]


def collect_keyless_blocks(readers: Sequence[SstReader]
                           ) -> Tuple[List[ColumnarBlock], dict]:
    """All columnar blocks of the snapshot, as ONE candidate sorted
    run: per-SST block runs are ordered by their first stored boundary
    key (newest-first install order is irrelevant for a disjoint set;
    interleaved/overlapping runs are caught by the chunk-safety check
    downstream, which this ordering deliberately feeds)."""
    runs: List[List[ColumnarBlock]] = []
    keyless = 0
    total = 0
    for r in readers:
        run: List[ColumnarBlock] = []
        for i in range(r.num_blocks()):
            cb = r.read_columnar(i)
            if cb is None:
                raise BypassIneligible(
                    REASON_NO_COLUMNAR,
                    f"{r.path}: block {i} has no columnar sidecar")
            total += 1
            if cb._keys is None:
                keyless += 1
            run.append(cb)
        if run:
            runs.append(run)

    def run_key(run: List[ColumnarBlock]) -> bytes:
        k0, _ = run[0].boundary_keys(materialize=False)
        return k0 if k0 is not None else b""

    runs.sort(key=run_key)
    blocks = [b for run in runs for b in run]
    return blocks, {"blocks": total, "keyless_blocks": keyless,
                    "ssts": len(readers)}


def bypass_scan_aggregate(
        blocks: Sequence[ColumnarBlock],
        where: Optional[tuple], aggs: Sequence[AggSpec],
        group, read_ht: int,
        kernel: Optional[ScanKernel] = None,
        chunk_rows: Optional[int] = None,
        prefilter_enabled: Optional[bool] = None,
        min_chunks: int = 3,
        grouped_out: Optional[dict] = None
        ) -> Tuple[tuple, np.ndarray, dict]:
    """Aggregate `blocks` at `read_ht` without touching the tserver.
    Returns (agg_values, counts, stats); raises BypassIneligible with a
    typed reason for every shape the engine cannot serve exactly.

    A :class:`DictGroupSpec` group serves KEYLESSLY too: string group
    columns ride as dictionary codes (stored v2 dict lanes or the
    per-block byte-level unique — row strings never decode), the
    grouped kernel aggregates into slot arrays, and the caller receives
    COMPACTED per-shard partials — ``grouped_out['group_values']``
    carries the decoded string keys aligned with the returned counts,
    ready for the shared group-keyed combine.  Slot overflow raises
    ``REASON_SLOT_OVERFLOW`` (the RPC path's interpreted GROUP BY
    serves the over-cardinality set)."""
    if not aggs:
        raise BypassIneligible(REASON_NOT_AGGREGATE)
    if isinstance(group, HashGroupSpec):
        raise BypassIneligible(REASON_HASH_GROUP)
    dict_group = isinstance(group, DictGroupSpec)
    if dict_group and not flags.get("grouped_pushdown_enabled"):
        raise BypassIneligible(REASON_GROUPED_OFF)
    # doc-path shapes rewrite onto shredded virtual lanes FIRST — the
    # keyless scanner then serves them like any derived column (the
    # shredded lanes need no key matrix, so zero key rebuilds hold)
    from ..docstore import pushdown as _doc
    if _doc.exprs_have_doc(where, aggs):
        if not flags.get("doc_shred_enabled"):
            raise BypassIneligible(REASON_DOC_OFF)
        from ..docstore.errors import DocIneligible
        try:
            where, aggs, _refs, blocks = _doc.prepare_doc_scan(
                where, aggs, blocks)
        except DocIneligible as e:
            raise BypassIneligible(
                REASON_DOC_SHAPE,
                e.reason + (f": {e.detail}" if e.detail else ""))
    from ..ops.expr import device_compatible, referenced_columns
    if where is not None and not device_compatible(where):
        raise BypassIneligible(REASON_EXPR_SHAPE, "where")
    for a in aggs:
        if a.expr is not None and not device_compatible(a.expr):
            raise BypassIneligible(REASON_EXPR_SHAPE, "aggregate expr")
    needed: set = set()
    if where is not None:
        referenced_columns(where, needed)
    for a in aggs:
        if a.expr is not None:
            referenced_columns(a.expr, needed)
    if dict_group:
        needed.update(group.cols)
    elif group is not None:
        needed.update(cid for cid, _, _ in group.cols)
    for b in blocks:
        for cid in needed:
            # varlen (string) columns are servable too: they ride as
            # dictionary codes (string predicates compare as integers,
            # DictGroupSpec keys aggregate as code strides); columns
            # with no columnar form at all stay typed-ineligible
            if not (cid in b.fixed or cid in b.pk or cid in b.varlen):
                raise BypassIneligible(
                    REASON_COLUMN_NOT_FIXED, f"column {cid}")
    # the ONE structural gate: every doc key lives wholly inside one
    # block of one globally-sorted disjoint unique-key run, proven from
    # stored boundary keys alone
    if not chunk_safe_mvcc(blocks):
        raise BypassIneligible(REASON_NOT_CHUNK_SAFE)
    if prefilter_enabled is None:
        prefilter_enabled = flags.get("bypass_prefilter_enabled")
    if kernel is None:
        from ..docdb.operations import _SHARED_KERNEL
        kernel = _SHARED_KERNEL
    rebuilds0 = KEY_REBUILD_STATS["rebuilds"]
    cols_sorted = sorted(needed)
    expanded = tuple(_expand_avg(aggs))
    minmax = [i for i, a in enumerate(expanded)
              if a.op in ("min", "max")]
    aggs_run = expanded + tuple(AggSpec("count", expanded[i].expr)
                                for i in minmax)
    # the near-data prefilter compacts blocks through the fused
    # FIXED-lane gather — compacted pseudo-blocks carry no varlen
    # lanes, so a scan whose columns ride as dictionary codes (string
    # predicates, DictGroupSpec group keys) must run unfiltered; the
    # streaming path makes the same call (compacted blocks would have
    # no dictionary remap entries)
    rides_codes = any(
        not all(cid in b.fixed or cid in b.pk for b in blocks)
        for cid in cols_sorted)
    pf = (make_prefilter(where, cols_sorted)
          if prefilter_enabled and not rides_codes else None)
    stats: dict = {}
    gout: Optional[dict] = {} if dict_group else None
    dict_out: dict = {}
    got = streaming_scan_aggregate(
        blocks, cols_sorted, where, aggs_run, group, read_ht,
        kernel=kernel, chunk_rows=chunk_rows, prefilter=pf,
        min_chunks=min_chunks, grouped_out=gout, dict_out=dict_out)
    group_dicts = None
    if got is None:
        got = _monolithic_twin(blocks, cols_sorted, where, aggs_run,
                               group, read_ht, kernel, pf,
                               dict_out=dict_out)
        if dict_group:
            got, group_dicts = got
        stats["path"] = "monolithic"
    else:
        if dict_group:
            if gout.get("spill"):
                raise BypassIneligible(
                    REASON_SLOT_OVERFLOW,
                    f"{gout['spill']} rows past "
                    f"{gout['num_slots']} slots")
            group_dicts = gout["dicts"]
        stats["path"] = "streaming"
        stats.update(LAST_STREAM_STATS)
    outs, counts = got
    from ..docdb.operations import _nullify_minmax, dict_minmax_decode
    outs = _nullify_minmax(expanded, minmax, outs)
    # dict-code MIN/MAX decode happens PER SHARD, before the session's
    # cross-shard combine — each shard merged its own dictionary, so
    # codes must never leave the shard
    outs = dict_minmax_decode(expanded, outs,
                              dict_out.get("dicts") or {})
    if dict_group:
        from ..ops.grouped_scan import decode_slot_groups
        outs, counts, gvals = decode_slot_groups(
            group, group_dicts, outs, counts)
        if grouped_out is not None:
            grouped_out["group_values"] = gvals
    stats["key_rebuilds"] = KEY_REBUILD_STATS["rebuilds"] - rebuilds0
    if pf is not None:
        from .prefilter import LAST_PREFILTER_STATS
        stats.setdefault("prefilter_rows_in",
                         LAST_PREFILTER_STATS["rows_in"])
        stats.setdefault("prefilter_rows_kept",
                         LAST_PREFILTER_STATS["rows_kept"])
    return outs, np.asarray(counts), stats


def bypass_plan_aggregate(
        blocks: Sequence[ColumnarBlock],
        where: Optional[tuple], aggs: Sequence[AggSpec],
        group, read_ht: int, join_wire,
        chunk_rows: Optional[int] = None,
        min_chunks: int = 3,
        grouped_out: Optional[dict] = None
        ) -> Tuple[tuple, np.ndarray, dict]:
    """Fused-plan (FK-equijoin) aggregate over a pinned snapshot —
    the bypass route of ops/plan_fusion.py.  The probe scan streams
    keylessly exactly like bypass_scan_aggregate (same chunk-safety
    gate, same shared bucket); the build side probes inside the fused
    program.  Raises BypassIneligible with a typed reason for every
    shape the engine cannot serve exactly; ``REASON_JOIN_SHAPE``
    carries the ops/join_scan typed reason in its detail."""
    from ..ops.join_scan import BUILD_COL_BASE, JoinIneligible
    from ..ops.plan_fusion import (default_plan_kernel,
                                   monolithic_plan_aggregate,
                                   streaming_plan_aggregate)
    if not aggs:
        raise BypassIneligible(REASON_NOT_AGGREGATE)
    if isinstance(group, HashGroupSpec):
        raise BypassIneligible(REASON_HASH_GROUP)
    if not flags.get("join_pushdown_enabled"):
        raise BypassIneligible(REASON_JOIN_OFF)
    dict_group = isinstance(group, DictGroupSpec)
    if dict_group and not flags.get("grouped_pushdown_enabled"):
        raise BypassIneligible(REASON_GROUPED_OFF)
    from ..ops.expr import device_compatible, referenced_columns
    if where is not None and not device_compatible(where):
        raise BypassIneligible(REASON_EXPR_SHAPE, "where")
    for a in aggs:
        if a.expr is not None and not device_compatible(a.expr):
            raise BypassIneligible(REASON_EXPR_SHAPE, "aggregate expr")
    needed: set = set()
    if where is not None:
        referenced_columns(where, needed)
    for a in aggs:
        if a.expr is not None:
            referenced_columns(a.expr, needed)
    if dict_group:
        needed.update(group.cols)
    elif group is not None:
        needed.update(cid for cid, _, _ in group.cols)
    needed = {c for c in needed if c < BUILD_COL_BASE}
    # multi-stage chains: only REAL probe-table columns gather from
    # blocks — a chain stage's probe lane is an earlier stage's payload
    # (>= BUILD_COL_BASE) and materializes inside the fused program
    from ..ops.join_scan import normalize_join
    for w in normalize_join(join_wire):
        if w.probe_col < BUILD_COL_BASE:
            needed.add(w.probe_col)
    for b in blocks:
        for cid in needed:
            if not (cid in b.fixed or cid in b.pk or cid in b.varlen):
                raise BypassIneligible(
                    REASON_COLUMN_NOT_FIXED, f"column {cid}")
    if not chunk_safe_mvcc(blocks):
        raise BypassIneligible(REASON_NOT_CHUNK_SAFE)
    kernel = default_plan_kernel()
    rebuilds0 = KEY_REBUILD_STATS["rebuilds"]
    cols_sorted = sorted(needed)
    expanded = tuple(_expand_avg(aggs))
    minmax = [i for i, a in enumerate(expanded)
              if a.op in ("min", "max")]
    aggs_run = expanded + tuple(AggSpec("count", expanded[i].expr)
                                for i in minmax)
    stats: dict = {}
    gout: Optional[dict] = {} if dict_group else None
    from ..docdb.operations import DocReadOperation
    try:
        got = streaming_plan_aggregate(
            blocks, cols_sorted, where, aggs_run, group, read_ht,
            join_wire, kernel=kernel, chunk_rows=chunk_rows,
            min_chunks=min_chunks, grouped_out=gout)
        if got is None:
            try:
                got = monolithic_plan_aggregate(
                    blocks, cols_sorted, where, aggs_run, group,
                    read_ht, join_wire, kernel=kernel,
                    grouped_out=gout)
            except KeyError as e:
                raise BypassIneligible(REASON_COLUMN_NOT_FIXED, str(e))
            stats["path"] = "monolithic"
        else:
            stats["path"] = "streaming"
    except JoinIneligible as e:
        raise BypassIneligible(REASON_JOIN_SHAPE, e.reason)
    except DocReadOperation._Unrewritable:
        raise BypassIneligible(
            REASON_EXPR_SHAPE,
            "string column outside a rewritable predicate shape")
    if dict_group and gout.get("spill"):
        raise BypassIneligible(
            REASON_SLOT_OVERFLOW,
            f"{gout['spill']} rows past {gout['num_slots']} slots")
    outs, counts = got
    from ..docdb.operations import _nullify_minmax
    outs = _nullify_minmax(expanded, minmax, outs)
    if dict_group:
        from ..ops.grouped_scan import decode_slot_groups
        outs, counts, gvals = decode_slot_groups(
            group, gout["dicts"], outs, counts)
        if grouped_out is not None:
            grouped_out["group_values"] = gvals
    stats["key_rebuilds"] = KEY_REBUILD_STATS["rebuilds"] - rebuilds0
    from ..ops.plan_fusion import LAST_PLAN_STATS
    # keep the session-scoped key_rebuilds (it covers block collection
    # too, not just the chunk pipeline)
    stats.update({k: v for k, v in LAST_PLAN_STATS.items()
                  if k not in ("path", "key_rebuilds")})
    return outs, np.asarray(counts), stats


def _monolithic_twin(blocks, cols_sorted, where, aggs_run, group,
                     read_ht, kernel, pf, dict_out: dict = None):
    """The under-min_chunks shape, mirroring the RPC monolithic
    aggregate path bit-for-bit (zone-prune gate, single bucket over the
    kept rows, unique_keys forced off for multi-block inputs, string
    predicates rewritten against the batch dictionaries) so bypass
    results stay byte-identical whichever shape the row count picks.
    Dict-grouped scans return ``((outs, counts), batch dictionaries)``
    — the caller decodes slots through the same dictionaries the group
    ids were encoded with."""
    from ..ops.scan import zone_prune_blocks
    kept = list(blocks)
    if where is not None and flags.get("zone_map_pruning"):
        # bypass blocks are always chunk-safe (the caller verified), so
        # pruning is unconditionally sound here
        kept, _ = zone_prune_blocks(kept, where)
    try:
        if pf is not None:
            batch = build_batch(
                pf(kept), cols_sorted,
                pad_to=bucket_rows(max(sum(b.n for b in kept), 1)),
                bounds_blocks=kept)
        else:
            batch = build_batch(kept, cols_sorted)
    except KeyError as e:
        # build_batch's documented fall-back contract: a varlen column
        # that can't dictionary-encode (binary / non-UTF8 payloads)
        # raises KeyError — typed here so client routing falls back to
        # the RPC path instead of crashing.  Scoped to the batch build
        # alone: a KeyError from kernel dispatch below would be a real
        # bug and must propagate, not masquerade as ineligibility.
        raise BypassIneligible(REASON_COLUMN_NOT_FIXED, str(e))
    if len(blocks) > 1:
        batch.unique_keys = False
    if dict_out is not None:
        dict_out["dicts"] = batch.dicts
    if batch.dicts and (where is not None
                        or any(a.expr is not None for a in aggs_run)):
        from ..docdb.operations import DocReadOperation
        try:
            where, aggs_run = DocReadOperation.rewrite_where_and_aggs(
                where, aggs_run, batch.dicts)
        except DocReadOperation._Unrewritable:
            raise BypassIneligible(
                REASON_EXPR_SHAPE, "string column outside a "
                "rewritable predicate shape")
    if isinstance(group, DictGroupSpec):
        from ..ops.grouped_scan import domain_product
        if any(c not in batch.dicts for c in group.cols):
            raise BypassIneligible(
                REASON_COLUMN_NOT_FIXED,
                "group column has no dictionary form")
        if domain_product(group, batch.dicts) >= 2 ** 31:
            raise BypassIneligible(
                REASON_SLOT_OVERFLOW,
                "group domain product exceeds 2^31 (group id would "
                "wrap)")
        outs, counts, _, spill = kernel.run(batch, where, aggs_run,
                                            group, read_ht)
        if int(spill) > 0:
            raise BypassIneligible(
                REASON_SLOT_OVERFLOW, f"{int(spill)} rows spilled")
        return (outs, counts), batch.dicts
    outs, counts, _ = kernel.run(batch, where, aggs_run, group, read_ht)
    return outs, counts
