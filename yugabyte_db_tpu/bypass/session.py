"""BypassSession: the user-facing face of the analytics bypass engine.

One session = one frozen read point over a set of tablet shards, pinned
against compaction/flush for the session's lifetime.  TPC-H Q1/Q6
shaped aggregates run through :func:`bypass_scan_aggregate` per shard
and combine across shards either host-side (the client-side partial
combine, byte-identical to the RPC fan-out's) or — when a device mesh
is available — via the psum/pmin/pmax collectives of
parallel/distributed_scan.py (the ICI combine the ROADMAP's
"scales with replicas" story points at).

The session NEVER touches the tserver: pins come from the storage
layer, files are opened directly, kernels dispatch from the calling
thread.  That is the structural isolation guarantee — analytics load
cannot queue behind (or ahead of) point traffic on the event loop.
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..ops.scan import AggSpec, _expand_avg, combine_agg_partials
from .errors import REASON_NO_SSTS, BypassIneligible
from .pinner import TabletSnapshot, pin_tablet
from .scan import (bypass_scan_aggregate, collect_keyless_blocks,
                   open_snapshot_readers)


def combine_partials(aggs: Sequence[AggSpec], parts: List[tuple],
                     counts_parts: List[np.ndarray]
                     ) -> Tuple[tuple, Optional[np.ndarray]]:
    """Host-side cross-shard combine — LITERALLY the client's RPC
    partial combine (`ops.scan.combine_agg_partials`, one shared
    implementation), applied in the same shard order, so bypass and
    RPC fan-out results cannot drift."""
    return combine_agg_partials(tuple(_expand_avg(aggs)), parts,
                                counts_parts)


class BypassSession:
    """Snapshot-consistent SST-direct analytics session.

    ``tablets``: the LOCAL tablet shard objects of a read replica
    co-located with the caller (pass them in the same order the RPC
    fan-out would visit, so host-combined results match bit-for-bit).
    TabletPeer objects are accepted too and are the right choice for
    consensus-served tablets: the pinner then waits on the peer's MVCC
    safe time so a write enqueued (with its HT already assigned) but
    not yet applied can never be missing from the snapshot.
    ``read_ht``: explicit read point; defaults to the newest tablet
    clock reading, ratcheted into every shard's clock by the pinner.

    Context manager; `close()` releases every SST lease (pinned files
    the store dropped meanwhile are physically reclaimed then).
    """

    def __init__(self, tablets: Sequence, read_ht: Optional[int] = None,
                 table_id: Optional[str] = None,
                 chunk_rows: Optional[int] = None,
                 prefilter: Optional[bool] = None,
                 min_chunks: int = 3):
        if not tablets:
            raise ValueError("BypassSession needs at least one tablet")
        shards = []                       # (tablet, safe_time_fn|None)
        for t in tablets:
            if hasattr(t, "safe_read_ht") and hasattr(t, "tablet"):
                shards.append((t.tablet, t.safe_read_ht))
            else:
                shards.append((t, None))
        auto_read_ht = read_ht is None
        if auto_read_ht:
            read_ht = max(t.clock.now().value for t, _ in shards)
        self.chunk_rows = chunk_rows
        self.prefilter = prefilter
        self.min_chunks = min_chunks
        self.snapshots: List[TabletSnapshot] = []
        self._readers = []          # keep mmaps alive for the session
        self._blocks: List[list] = []
        self._closed = False
        try:
            # a session-chosen read point follows the RPC path's
            # server-assigned semantics: rows inside the clock-
            # uncertainty window (read_ht, read_ht + skew] force a
            # restart at the ambiguous time — re-PIN, since rows at the
            # higher point must be on disk too.  Explicit caller read
            # points are snapshot reads and never restart, exactly like
            # the RPC path; the final attempt accepts (the multi_read
            # bounded-restart discipline).
            for attempt in range(3 if auto_read_ht else 1):
                self._open_shards(shards, read_ht, table_id)
                if not auto_read_ht or attempt == 2:
                    break
                amb = self._max_ambiguous_ht(read_ht)
                if amb is None:
                    break
                self._release_shards()
                read_ht = amb
            self.read_ht = read_ht
        except BaseException:
            self.close()
            raise

    def _open_shards(self, shards, read_ht: int, table_id) -> None:
        for t, safe_fn in shards:
            snap = pin_tablet(t, read_ht=read_ht, table_id=table_id,
                              allow_empty=True, safe_time_fn=safe_fn)
            self.snapshots.append(snap)
        for snap in self.snapshots:
            readers = open_snapshot_readers(snap)
            blocks, bstats = collect_keyless_blocks(readers)
            snap.stats.update(bstats)
            self._readers.append(readers)
            self._blocks.append(blocks)

    def _release_shards(self) -> None:
        for snap in self.snapshots:
            snap.close()
        self.snapshots = []
        self._readers = []
        self._blocks = []

    def _max_ambiguous_ht(self, read_ht: int):
        """Newest hybrid time inside the clock-uncertainty window
        across every pinned block, or None when the window is clean
        (the coarse whole-block check the RPC aggregate paths use)."""
        from ..docdb.operations import _skew_window_ht
        window_hi = np.uint64(read_ht + _skew_window_ht())
        lo = np.uint64(read_ht)
        amb = None
        for blocks in self._blocks:
            for b in blocks:
                a = b.ht[(b.ht > lo) & (b.ht <= window_hi)]
                if len(a):
                    m = int(a.max())
                    amb = m if amb is None else max(amb, m)
        return amb

    # ------------------------------------------------------------------
    def scan_aggregate(self, where, aggs: Sequence[AggSpec],
                       group=None, combine: str = "host",
                       grouped_out: Optional[dict] = None,
                       join=None
                       ) -> Tuple[tuple, Optional[np.ndarray], dict]:
        """Run one aggregate scan at the session read point across all
        pinned shards.  combine='host' reproduces the RPC fan-out's
        partial combine exactly; combine='mesh' psum-combines on a
        device mesh (one device per shard; raises ValueError when the
        backend has too few devices — no silent fallback, callers pick
        deliberately).  Raises BypassIneligible (typed) when any shard
        can't be served exactly.

        Dict-grouped scans (:class:`DictGroupSpec`) merge per-shard
        COMPACTED partials by group key through
        ``ops.scan.combine_grouped_partials`` — the exact function the
        client's RPC fan-out combine uses, so bypass and RPC grouped
        results cannot drift; pass ``grouped_out`` (a dict) to receive
        ``{'group_values': per-column key arrays}`` aligned with the
        returned counts.  Mesh combine does not serve grouped scans
        (per-shard dictionaries don't align into one psum lattice)."""
        if self._closed:
            raise RuntimeError("BypassSession is closed")
        from ..ops.grouped_scan import DictGroupSpec
        dict_group = isinstance(group, DictGroupSpec)
        if combine == "mesh":
            if dict_group or join is not None:
                raise ValueError(
                    "mesh combine does not serve dict-grouped or "
                    "join scans; use combine='host'")
            return self._scan_mesh(where, aggs, group)
        if combine != "host":
            raise ValueError(f"unknown combine mode {combine!r}")
        parts, counts_parts, grouped_parts = [], [], []
        stats = self.stats()
        stats.update(key_rebuilds=0, prefilter_rows_in=0,
                     prefilter_rows_kept=0, combine="host",
                     shards_scanned=0)
        for blocks in self._blocks:
            if not blocks:
                continue            # empty shard: combine identity
            gout: dict = {}
            if join is not None:
                from .scan import bypass_plan_aggregate
                outs, counts, sstats = bypass_plan_aggregate(
                    blocks, where, aggs, group, self.read_ht, join,
                    chunk_rows=self.chunk_rows,
                    min_chunks=self.min_chunks,
                    grouped_out=gout if dict_group else None)
            else:
                outs, counts, sstats = bypass_scan_aggregate(
                    blocks, where, aggs, group, self.read_ht,
                    chunk_rows=self.chunk_rows,
                    prefilter_enabled=self.prefilter,
                    min_chunks=self.min_chunks,
                    grouped_out=gout if dict_group else None)
            parts.append(outs)
            counts_parts.append(counts)
            if dict_group:
                grouped_parts.append(
                    (outs, counts, gout["group_values"]))
            stats["shards_scanned"] += 1
            stats["key_rebuilds"] += sstats.get("key_rebuilds", 0)
            stats["prefilter_rows_in"] += sstats.get(
                "prefilter_rows_in", 0)
            stats["prefilter_rows_kept"] += sstats.get(
                "prefilter_rows_kept", 0)
            stats.setdefault("paths", []).append(sstats.get("path"))
        if not parts:
            raise BypassIneligible(REASON_NO_SSTS,
                                   "every shard is empty")
        if dict_group:
            from ..ops.scan import combine_grouped_partials
            t0 = time.perf_counter()
            outs, counts, gvals = combine_grouped_partials(
                tuple(_expand_avg(aggs)), grouped_parts)
            stats["combine_s"] = round(time.perf_counter() - t0, 4)
            if grouped_out is not None:
                grouped_out["group_values"] = gvals
            return outs, counts, stats
        outs, counts = combine_partials(aggs, parts, counts_parts)
        return outs, counts, stats

    def _scan_mesh(self, where, aggs, group):
        """psum-combine across shards on a device mesh: one tablet
        shard per device, partial aggregates combined over ICI by
        parallel/distributed_scan.py.  Serves sum/count/avg shapes with
        the distributed kernel's documented accumulation contract (no
        per-chunk streaming, no prefilter — the sharded batch is built
        whole)."""
        import jax

        from ..parallel.distributed_scan import (
            build_sharded_batch, distributed_scan_aggregate)
        from ..parallel.mesh import tablet_mesh
        shards = [b for b in self._blocks if b]
        if not shards:
            raise BypassIneligible(REASON_NO_SSTS,
                                   "every shard is empty")
        devices = jax.devices()
        if len(devices) < len(shards):
            raise ValueError(
                f"mesh combine needs {len(shards)} devices, "
                f"backend has {len(devices)}")
        from ..ops.expr import referenced_columns
        needed: set = set()
        if where is not None:
            referenced_columns(where, needed)
        for a in aggs:
            if a.expr is not None:
                referenced_columns(a.expr, needed)
        if group is not None:
            needed.update(cid for cid, _, _ in group.cols)
        from ..ops.stream_scan import chunk_safe_mvcc
        from .errors import REASON_NOT_CHUNK_SAFE
        for blocks in shards:
            if not chunk_safe_mvcc(blocks):
                raise BypassIneligible(REASON_NOT_CHUNK_SAFE)
        tm = tablet_mesh(num_tablet_shards=len(shards),
                         num_block_shards=1,
                         devices=devices[:len(shards)])
        batch = build_sharded_batch(tm, shards, sorted(needed))
        outs, counts = distributed_scan_aggregate(
            batch, where, tuple(aggs), group, self.read_ht)
        stats = self.stats()
        stats.update(combine="mesh", shards_scanned=len(shards))
        return tuple(np.asarray(o) for o in outs), \
            np.asarray(counts), stats

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        keyless = sum(s.stats.get("keyless_blocks", 0)
                      for s in self.snapshots)
        return {"read_ht": self.read_ht,
                "shards": len(self.snapshots),
                "pinned_files": sum(len(s.sst_paths)
                                    for s in self.snapshots),
                "blocks": sum(s.stats.get("blocks", 0)
                              for s in self.snapshots),
                "keyless_blocks": keyless}

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for snap in self.snapshots:
            snap.close()

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "BypassSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
