"""Near-data predicate pre-filter: drop provably-unmatched rows from a
chunk's encoded lanes BEFORE batch formation.

The WHERE tree's top-level AND conjuncts of shape ``col <op> const`` /
``BETWEEN`` collapse into one conservative inclusive interval per
column.  A single GIL-released native pass
(storage/native_lib.prefilter_ranges, numpy oracle fallback) evaluates
the intervals over each block's fixed-width lanes and the surviving
rows gather — through the same fused native gather the batch builder
uses — into a compacted block.  Everything the filter drops is a row
the scan kernel could never have matched:

  * integer lanes compare exactly (the kernel keeps integer dtypes);
  * float lanes widen every bound one f32 ulp outward and treat strict
    bounds as inclusive (the kernel may evaluate in the device float
    dtype — the zone-map ``_f32_widen`` discipline);
  * NULL rows fail their conjunct, exactly as the kernel's NULL
    comparison semantics do;
  * OR/IN/NOT/expression shapes contribute no interval (never prune).

Because dropped rows contribute exactly zero to every aggregate lane,
and the batch builder keeps the unfiltered chunk's dtype policy, pad
bucket and static-scale bounds (``bounds_blocks``), the filtered scan
is byte-identical to the unfiltered one — it just moves fewer bytes.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..ops.scan import _f32_widen
from ..storage import native_lib
from ..storage.columnar import ColumnarBlock

#: (lo, lo_strict, hi, hi_strict) — open bounds as ±inf.  Bounds keep
#: their ORIGINAL python type: int constants stay exact ints (float
#: coercion would round above 2^53 and could drop kernel-matched
#: rows — the same exact-int discipline as ops/scan._zone_interval);
#: python's int-vs-float comparison is exact, so mixed intersections
#: are safe.
_Interval = Tuple[object, bool, object, bool]

_INF = float("inf")

#: most recent prefilter tally (profile/bench scripts read it)
LAST_PREFILTER_STATS = {"rows_in": 0, "rows_kept": 0, "blocks": 0,
                        "blocks_compacted": 0}


def _const_num(node):
    if (isinstance(node, (tuple, list)) and node
            and node[0] == "const"
            and isinstance(node[1], (int, float))
            and not isinstance(node[1], bool)
            # NaN constants: the conjunct can never be true, but
            # "never prune on unprovable" is the discipline — skip it
            # and let the kernel evaluate (±inf stays: it clamps to an
            # empty or unbounded range below, both sound)
            and not (isinstance(node[1], float)
                     and np.isnan(node[1]))):
        return node[1]
    return None


def _col_id(node):
    if isinstance(node, (tuple, list)) and node and node[0] == "col":
        return node[1]
    return None


def _intersect(a: _Interval, b: _Interval) -> _Interval:
    lo, los, hi, his = a
    blo, blos, bhi, bhis = b
    if blo > lo or (blo == lo and blos):
        lo, los = blo, blos
    if bhi < hi or (bhi == hi and bhis):
        hi, his = bhi, bhis
    return (lo, los, hi, his)


def extract_intervals(where) -> Dict[int, _Interval]:
    """col id -> interval implied by the top-level AND conjuncts of
    `where`.  Only shapes that MUST hold for the row to match
    contribute; everything else is ignored (the kernel still applies
    the full predicate, the prefilter only needs to be conservative)."""
    out: Dict[int, _Interval] = {}
    if where is None:
        return out

    def add(cid, iv: _Interval):
        out[cid] = _intersect(out[cid], iv) if cid in out else iv

    def walk(node):
        if not isinstance(node, (tuple, list)) or not node:
            return
        kind = node[0]
        if kind == "and":
            for c in node[1:]:
                walk(c)
            return
        if kind == "between":
            cid = _col_id(node[1])
            lo, hi = _const_num(node[2]), _const_num(node[3])
            if cid is not None and lo is not None and hi is not None:
                add(cid, (lo, False, hi, False))
            return
        if kind != "cmp":
            return
        op, l, r = node[1], node[2], node[3]
        cid, v = _col_id(l), _const_num(r)
        if cid is None:
            cid, v = _col_id(r), _const_num(l)
            if cid is None or v is None:
                return
            op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                  "eq": "eq", "ne": "ne"}.get(op)
            if op is None:
                return
        if v is None:
            return
        if op == "eq":
            add(cid, (v, False, v, False))
        elif op == "lt":
            add(cid, (-_INF, False, v, True))
        elif op == "le":
            add(cid, (-_INF, False, v, False))
        elif op == "gt":
            add(cid, (v, True, _INF, False))
        elif op == "ge":
            add(cid, (v, False, _INF, False))
        # ne: no interval

    walk(where)
    return out


def _clamp_to_lane(iv: _Interval, dtype: np.dtype
                   ) -> Optional[Tuple[object, object]]:
    """Interval -> inclusive (lo, hi) in the lane's own domain, or None
    when the lane can't be range-tested safely.  Integer lanes resolve
    strictness exactly; float lanes widen to the f32 envelope and treat
    strict bounds as inclusive (conservative both ways)."""
    lo, los, hi, his = iv
    if dtype.kind in "iu":
        try:
            info = np.iinfo(dtype)
        except ValueError:
            return None
        if lo == _INF or hi == -_INF:
            # v >= +inf / v <= -inf: nothing matches; (1, 0) is an
            # empty range whose bounds are valid for every int dtype
            return (1, 0)
        if lo == -_INF:
            ilo = int(info.min)
        elif isinstance(lo, int):
            # exact-int bounds stay exact (no float round-trip above
            # 2^53 — python ints are arbitrary precision)
            ilo = lo + 1 if los else lo
        else:
            f = np.floor(lo)
            # v > 5.0 -> v >= 6; v > 4.5 and v >= 4.5 both -> v >= 5
            ilo = int(f) + 1 if (los and lo == f) else int(np.ceil(lo))
        if hi == _INF:
            ihi = int(info.max)
        elif isinstance(hi, int):
            ihi = hi - 1 if his else hi
        else:
            c = np.ceil(hi)
            ihi = int(c) - 1 if (his and hi == c) else int(np.floor(hi))
        if ilo > ihi:
            # contradictory interval: canonical empty range (valid
            # bounds for every int dtype, so the native path serves it)
            return (1, 0)
        return (max(ilo, int(info.min)), min(ihi, int(info.max)))
    if dtype.kind == "f":
        wlo = lo if lo == -_INF else _f32_widen(lo, lo)[0]
        whi = hi if hi == _INF else _f32_widen(hi, hi)[1]
        return (wlo, whi)
    return None


def block_predicates(block: ColumnarBlock,
                     intervals: Dict[int, _Interval]):
    """Resolve the per-column intervals against one block's lanes:
    list of (values, nulls, lo, hi) jobs for the native range pass.
    Columns the block lacks in fixed-width form contribute nothing."""
    preds = []
    for cid, iv in intervals.items():
        if cid in block.fixed:
            vals, nulls = block.fixed[cid]
        elif cid in block.pk:
            vals, nulls = block.pk[cid], None
        else:
            continue
        vals = np.asarray(vals)
        rng = _clamp_to_lane(iv, vals.dtype)
        if rng is None:
            continue
        preds.append((vals,
                      np.asarray(nulls) if nulls is not None else None,
                      rng[0], rng[1]))
    return preds


def compact_block(block: ColumnarBlock, keep_idx: np.ndarray,
                  columns: Sequence[int]) -> ColumnarBlock:
    """Gather the kept rows of `block` (needed columns + MVCC lanes)
    into a fresh owned block via ONE fused native gather call
    (storage/native_lib.gather_columns, numpy fallback inside)."""
    m = len(keep_idx)
    jobs = []

    def gather(src: np.ndarray) -> np.ndarray:
        src = np.ascontiguousarray(src)
        dst = np.empty((m,) + src.shape[1:], src.dtype)
        jobs.append((src, dst, keep_idx, None))
        return dst

    key_hash = gather(block.key_hash)
    ht = gather(block.ht)
    write_id = gather(block.write_id)
    tombstone = gather(block.tombstone)
    pk = {cid: gather(block.pk[cid]) for cid in block.pk
          if cid in columns}
    fixed = {cid: (gather(v), gather(nu))
             for cid, (v, nu) in block.fixed.items() if cid in columns}
    native_lib.gather_columns(jobs)
    out = ColumnarBlock.from_arrays(
        schema_version=block.schema_version, key_hash=key_hash, ht=ht,
        write_id=write_id, pk=pk, fixed=fixed, tombstone=tombstone,
        unique_keys=block.unique_keys)
    return out


def make_prefilter(where, columns: Sequence[int]):
    """Build the per-chunk prefilter callable for
    streaming_scan_aggregate, or None when `where` yields no usable
    interval (nothing to pre-filter on)."""
    intervals = extract_intervals(where)
    if not intervals:
        return None
    cols = tuple(columns)
    LAST_PREFILTER_STATS.update(rows_in=0, rows_kept=0, blocks=0,
                                blocks_compacted=0)

    def prefilter(chunk):
        out = []
        for b in chunk:
            LAST_PREFILTER_STATS["blocks"] += 1
            LAST_PREFILTER_STATS["rows_in"] += b.n
            preds = block_predicates(b, intervals)
            if not preds or b.n == 0:
                LAST_PREFILTER_STATS["rows_kept"] += b.n
                out.append(b)
                continue
            keep = native_lib.prefilter_mask(preds, b.n)
            idx = np.flatnonzero(keep).astype(np.int64)
            if len(idx) == b.n:
                LAST_PREFILTER_STATS["rows_kept"] += b.n
                out.append(b)
                continue
            LAST_PREFILTER_STATS["rows_kept"] += len(idx)
            LAST_PREFILTER_STATS["blocks_compacted"] += 1
            out.append(compact_block(b, idx, cols))
        return out

    return prefilter
