"""Snapshot pinner: a frozen, snapshot-consistent read point plus a
leased SST file list that survives concurrent compaction and flush.

The invariant a pinned snapshot guarantees: every row version with
``ht <= read_ht`` lives in the pinned SST files.  It holds because

  1. ``read_ht`` is taken from the tablet clock FIRST (and the clock is
     ratcheted past an externally supplied read point), so every write
     applied after the pin gets a strictly larger hybrid time — such
     rows may land in pinned SSTs (a racing flush) but MVCC filtering
     at ``read_ht`` makes them invisible, never wrong;
  2. the memtable is flushed until empty, and the pin itself
     (``LsmStore.pin_ssts(require_empty_memtable=True)``) re-verifies
     emptiness under the same lock that installs flush output — so no
     row at or below the read point can still be memory-only when the
     file list is captured;
  3. the lease refcounts the files against the store's GC: compaction
     replaces the live set but the physical unlink of pinned inputs is
     deferred until release (storage/lsm.py), and a crashed leaseholder
     leaves only unmanifested files the next open sweeps.

This is what turns "analytics must not queue behind point traffic"
from a scheduling policy into a structural guarantee: after ``pin``,
the scan never talks to the tserver again.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..storage.lsm import SstLease
from ..utils.hybrid_time import HybridTime
from ..utils.trace import wait_status
from .errors import REASON_MEMTABLE_ACTIVE, REASON_NO_SSTS, BypassIneligible


@dataclass
class TabletSnapshot:
    """One tablet's frozen read point + leased SST file set.  The codec
    rides along for schema access (column ids/dtypes); the SST files
    themselves are opened by the scanner, NOT through the store."""

    tablet_id: str
    read_ht: int
    sst_paths: List[str]
    lease: SstLease
    codec: object                      # docdb TableCodec
    stats: dict = field(default_factory=dict)

    def close(self) -> None:
        self.lease.release()

    @property
    def closed(self) -> bool:
        return self.lease.released


def pin_tablet(tablet, read_ht: Optional[int] = None,
               table_id: Optional[str] = None,
               max_flush_attempts: int = 4,
               allow_empty: bool = False,
               safe_time_fn=None, safe_wait_s: float = 10.0
               ) -> TabletSnapshot:
    """Pin `tablet` at a frozen read point.  Raises BypassIneligible
    (memtable_active) when rows at/below the read point cannot be
    proven on-disk after ``max_flush_attempts`` flushes, or (no_ssts)
    when the tablet has no SST files at all (unless ``allow_empty``).

    ``safe_time_fn``: callable(now_value) -> MVCC safe read HT (a
    TabletPeer's ``safe_read_ht``).  REQUIRED for correctness when the
    tablet serves a consensus pipeline: a write is ASSIGNED its hybrid
    time at enqueue (TabletPeer.write), so a row with ht <= read_ht
    can sit in the raft queue — invisible to the memtable — while we
    pin.  Polling until safe time passes the read point closes that
    window exactly like the RPC read path's wait; later writes are
    then assigned ht > read_ht by clock monotonicity.  Direct-apply
    tablets (bulk load / apply_write callers, no queue) need no
    safe_time_fn — their writes hit the memtable synchronously."""
    if read_ht is None:
        read_ht = tablet.clock.now().value
    else:
        # ratchet: writes applied after this line can never be assigned
        # a hybrid time at or below the externally chosen read point
        tablet.clock.update(HybridTime(read_ht))
    if safe_time_fn is not None:
        deadline = time.monotonic() + safe_wait_s
        # FIRST call unguarded: a mis-wired safe_time_fn (wrong arity,
        # wrong object) must surface as its real error, not burn the
        # whole wait and masquerade as memtable_active
        if safe_time_fn(tablet.clock.now().value) < read_ht:
            with wait_status("SafeTime_Wait", component="bypass"):
                while True:
                    try:
                        if safe_time_fn(
                                tablet.clock.now().value) >= read_ht:
                            break
                    except Exception:   # noqa: BLE001 — transient
                        pass            # cross-thread misread of in-
                        #                 flight state: re-poll
                    if time.monotonic() > deadline:
                        raise BypassIneligible(
                            REASON_MEMTABLE_ACTIVE,
                            f"tablet {tablet.tablet_id}: in-flight "
                            "writes below the read point did not drain")
                    time.sleep(0.002)
    store = tablet.regular
    lease = None
    for attempt in range(max_flush_attempts):
        if attempt:
            # another thread's flush is mid-install (frozen memtable
            # drained off-lock); yield rather than spin
            time.sleep(0.005 * attempt)
        if not store.memtable_empty():
            # best-effort drain (wait=False): a stuck foreign flush
            # holding the store's flush IO lock must exhaust the
            # bounded attempts into the typed refusal below, not hang
            # the pin forever behind a dead disk
            tablet.flush(wait=False)
        lease = store.pin_ssts(require_empty_memtable=True)
        if lease is not None:
            break
    if lease is None:
        raise BypassIneligible(
            REASON_MEMTABLE_ACTIVE,
            f"tablet {tablet.tablet_id}: memtable still holds rows "
            f"after {max_flush_attempts} flush attempts")
    if not lease.paths and not allow_empty:
        lease.release()
        raise BypassIneligible(
            REASON_NO_SSTS, f"tablet {tablet.tablet_id} has no SSTs")
    codec = tablet._codec_for(table_id) if table_id else tablet.codec
    return TabletSnapshot(
        tablet_id=tablet.tablet_id, read_ht=read_ht,
        sst_paths=list(lease.paths), lease=lease, codec=codec,
        stats={"flush_attempts": attempt + 1,
               "pinned_files": len(lease.paths)})
