"""Typed bypass-ineligibility: every reason a tablet falls back to the
RPC scan path is a named constant, carried on the exception and counted
per session, so callers (and tests) can assert WHY a scan refused to
bypass instead of pattern-matching error strings.

The contract mirrors the streaming-scan fallbacks (ops/stream_scan.py):
a bypass refusal is never an error to the user — the client routes the
query back through the ordinary RPC path, which serves every shape.
"""
from __future__ import annotations

#: master switch off (client-level routing refusal)
REASON_FLAG_OFF = "flag_off"
#: memtable (active or frozen) still holds rows after the flush
#: attempts — rows at or below the read point may not be on disk yet
REASON_MEMTABLE_ACTIVE = "memtable_active"
#: the tablet has no SST files (nothing to scan directly; the RPC path
#: answers from the memtable)
REASON_NO_SSTS = "no_ssts"
#: an SST block lacks a columnar sidecar (row-format-only data)
REASON_NO_COLUMNAR = "no_columnar_block"
#: block sequence is not provably one disjoint sorted unique-key run
#: (overlapping SSTs, duplicate doc keys, or missing boundary keys)
REASON_NOT_CHUNK_SAFE = "not_chunk_safe"
#: a referenced column exists only in varlen/dictionary form — the
#: keyless scanner serves fixed-width lanes only
REASON_COLUMN_NOT_FIXED = "column_not_fixed"
#: hash-grouped aggregates don't combine densely across shards
REASON_HASH_GROUP = "hash_group"
#: the expression shape can't compile to the device kernel
REASON_EXPR_SHAPE = "expr_shape"
#: no aggregates in the request (the bypass engine serves
#: scan-and-aggregate shapes only, not row streams)
REASON_NOT_AGGREGATE = "not_aggregate"
#: dict-grouped scan overflowed the device slot budget — the RPC path's
#: interpreted GROUP BY serves the over-cardinality group set
REASON_SLOT_OVERFLOW = "grouped_slot_overflow"
#: dict-grouped scan while grouped_pushdown_enabled is off — the RPC
#: path's interpreted GROUP BY is the flag-off contract
REASON_GROUPED_OFF = "grouped_pushdown_off"
#: join request while join_pushdown_enabled / plan fusion is off — the
#: RPC path's interpreted join is the flag-off contract
REASON_JOIN_OFF = "join_pushdown_off"
#: the shipped build side can't be served exactly by the device join
#: (duplicate keys, oversized table, unsupported key type) — carries
#: the ops/join_scan typed reason in `detail`
REASON_JOIN_SHAPE = "join_shape"
#: doc-path request while doc_shred_enabled is off — the RPC path's
#: interpreted extractor is the flag-off contract
REASON_DOC_OFF = "doc_shred_off"
#: a doc-path shape the shredded lanes can't serve bit-identically
#: (unshredded/heterogeneous path, text-order compare over a numeric
#: lane, ...) — carries the docstore typed reason in `detail`
REASON_DOC_SHAPE = "doc_shape"

ALL_REASONS = (
    REASON_FLAG_OFF, REASON_MEMTABLE_ACTIVE, REASON_NO_SSTS,
    REASON_NO_COLUMNAR, REASON_NOT_CHUNK_SAFE, REASON_COLUMN_NOT_FIXED,
    REASON_HASH_GROUP, REASON_EXPR_SHAPE, REASON_NOT_AGGREGATE,
    REASON_SLOT_OVERFLOW, REASON_GROUPED_OFF, REASON_JOIN_OFF,
    REASON_JOIN_SHAPE, REASON_DOC_OFF, REASON_DOC_SHAPE,
)


class BypassIneligible(Exception):
    """This tablet/query cannot be served by the bypass reader; the
    caller falls back to the RPC path.  `reason` is one of the
    REASON_* constants; `detail` is free-form context for logs."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        self.detail = detail
        super().__init__(f"bypass ineligible: {reason}"
                         + (f" ({detail})" if detail else ""))
