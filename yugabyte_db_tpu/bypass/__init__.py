"""Analytics bypass reader: snapshot-consistent SST-direct scans that
never touch the tserver hot path.

The subsystem in one breath: :func:`pin_tablet` freezes a read point
and leases the SST file set against file GC (storage/lsm.py refcount
lease); :mod:`bypass.scan` opens the leased files directly and streams
their v2 columnar blocks — keyless, gated on stored k0/k1 boundary
keys — through the shared pow2-bucket kernel pipeline with a near-data
predicate pre-filter (:mod:`bypass.prefilter`, GIL-released native
range pass) compacting rows before batch formation; and
:class:`BypassSession` fans that out across tablet shards, combining
partials host-side (byte-identical to the RPC fan-out) or over a
device mesh (parallel/distributed_scan.py psum).

Layering is the point: this package must not import ``tserver``,
``sched`` or ``rpc`` — enforced by the tools/analyze ``layering``
pass.  Ineligible shapes raise :class:`BypassIneligible` with a typed
reason and callers fall back to the RPC path, which serves everything.
"""
from .errors import (ALL_REASONS, REASON_COLUMN_NOT_FIXED,
                     REASON_EXPR_SHAPE, REASON_FLAG_OFF,
                     REASON_GROUPED_OFF, REASON_HASH_GROUP,
                     REASON_MEMTABLE_ACTIVE, REASON_NO_COLUMNAR,
                     REASON_NO_SSTS, REASON_NOT_AGGREGATE,
                     REASON_NOT_CHUNK_SAFE, REASON_SLOT_OVERFLOW,
                     BypassIneligible)
from .pinner import TabletSnapshot, pin_tablet
from .scan import (bypass_scan_aggregate, collect_keyless_blocks,
                   open_snapshot_readers)
from .session import BypassSession, combine_partials

__all__ = [
    "ALL_REASONS", "BypassIneligible", "BypassSession",
    "REASON_COLUMN_NOT_FIXED", "REASON_EXPR_SHAPE", "REASON_FLAG_OFF",
    "REASON_GROUPED_OFF", "REASON_HASH_GROUP", "REASON_MEMTABLE_ACTIVE",
    "REASON_NO_COLUMNAR", "REASON_NO_SSTS", "REASON_NOT_AGGREGATE",
    "REASON_NOT_CHUNK_SAFE", "REASON_SLOT_OVERFLOW",
    "TabletSnapshot", "bypass_scan_aggregate", "collect_keyless_blocks",
    "combine_partials", "open_snapshot_readers", "pin_tablet",
]
