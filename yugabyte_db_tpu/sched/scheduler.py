"""RequestScheduler: bounded lanes + dynamic micro-batching.

Sits between RPC dispatch (tserver/tablet_server.py) and tablet
execution.  Responsibilities:

1. ADMISSION: every lane has a depth bound and a memory-based soft
   limit.  Past either, the request is shed IMMEDIATELY with a typed
   SERVICE_UNAVAILABLE carrying retry_after_ms (estimated from the
   lane's backlog x EWMA service time) — overload turns into fast,
   client-visible pushback instead of unbounded queue growth and
   latency collapse (reference analog: rpc/service_pool.cc queue
   limits + "server is overloaded" responses).

2. MICRO-BATCHING: queued work coalesces into groups —
   - same-tablet plain writes merge into ONE WriteRequest: one Raft
     item (one WAL append) and one tablet apply for the whole group
     (group commit; reference: Log group commit, consensus/log.cc
     TaskStream — ours merges one level higher so the per-request
     docdb encode/apply overhead amortizes too);
   - same-signature scans execute ONCE and fan the response out to
     every waiter; the signature is exactly what keys the ops/scan.py
     jitted-kernel cache, so a coalesced group is one cached kernel
     launch instead of N.
   Groups accrete while queued (zero added latency when idle) plus an
   ADAPTIVE window when the worker dequeues them: if the lane's recent
   arrival rate suggests the batch would grow, the worker waits
   expected-fill-time, bounded by max_wait_us and max_batch.

3. FAIRNESS: lanes have independent worker pools, so maintenance work
   can never occupy the dispatch slots foreground point reads need.

Fault injection (utils/fault_injection.py): armed lane stalls hold a
lane's workers before dispatch; forced sheds make admission reject —
both let tests drive overload behavior deterministically.
"""
from __future__ import annotations

import asyncio
import time
from typing import Callable, Dict, List, Optional

from ..rpc.messenger import RECEIVED_AT, RpcError
from ..utils import fault_injection as fi
from ..utils import flags, metrics
from ..utils.tasks import drain_all
from ..utils.trace import TRACE, TRACES, wait_status
from .batching import (PointReadItem, ScanItem, WriteItem,
                       dispatch_point_read_group, dispatch_scan_group,
                       dispatch_write_group)
from .lanes import (DEFAULT_CONFIGS, Lane, LaneConfig,
                    classify_read as classify_read_wire)


class OverloadError(RpcError):
    """Typed overload shed: SERVICE_UNAVAILABLE + retry_after_ms.
    Crosses the wire intact (rpc/messenger.py carries retry_after_ms in
    the error payload); client/client.py turns it into jittered
    exponential backoff."""

    def __init__(self, message: str, retry_after_ms: int):
        super().__init__(message, "SERVICE_UNAVAILABLE")
        self.retry_after_ms = max(1, int(retry_after_ms))


def canon(node):
    """Hashable canonical form of a wire payload (dicts key-sorted
    recursively) — the scan-coalescing signature.  Includes read_ht:
    requests with an explicit read point only coalesce with the SAME
    read point (identical snapshot)."""
    if isinstance(node, dict):
        return tuple((k, canon(v)) for k, v in sorted(node.items()))
    if isinstance(node, (list, tuple)):
        return tuple(canon(v) for v in node)
    return node


class _Ewma:
    __slots__ = ("value", "alpha")

    def __init__(self, alpha: float = 0.2, initial: float = 0.0):
        self.value = initial
        self.alpha = alpha

    def update(self, x: float) -> float:
        self.value = (x if self.value == 0.0
                      else self.value + self.alpha * (x - self.value))
        return self.value


class _Group:
    """One schedulable unit: 1..max_batch requests sharing a dispatch.
    `items` are (payload, future, cost_bytes, enqueue_t) tuples.  The
    lane queue carries the GROUP OBJECT (not its key): a key may be
    re-queued for a fresh group once this one fills, and the two must
    dispatch independently."""

    __slots__ = ("key", "items", "started")

    def __init__(self, key):
        self.key = key
        self.items: List[tuple] = []
        self.started = False


class _LaneState:
    def __init__(self, owner: str, lane: Lane, cfg: LaneConfig):
        self.lane = lane
        self.cfg = cfg
        self.queue: asyncio.Queue = asyncio.Queue()
        self.groups: Dict[object, _Group] = {}
        self.inflight = 0
        self.queued = 0
        self.queued_bytes = 0
        self.service_ms = _Ewma(initial=1.0)
        self.arrival_interval_s = _Ewma()
        self.last_arrival: Optional[float] = None
        ent = metrics.REGISTRY.entity("sched", f"{owner}:{lane.value}",
                                      server=owner, lane=lane.value)
        self.m_admitted = ent.counter("sched_admitted")
        self.m_shed = ent.counter("sched_shed")
        self.m_depth = ent.gauge("sched_queue_depth")
        self.m_wait = ent.histogram("sched_wait_us")
        self.m_batch = ent.histogram("sched_batch_size")
        self.m_occupancy = ent.histogram("sched_window_occupancy_pct")
        self.m_fanin = ent.histogram("sched_group_commit_fanin")
        # groups dispatched per fused worker wakeup (>1 = cross-tablet
        # fusion actually collapsed loop sweeps)
        self.m_fused_wakeup = ent.histogram("sched_fused_groups_per_wakeup")

    @property
    def depth(self) -> int:
        return self.queued + self.inflight

    def note_arrival(self) -> None:
        # frame-arrival stamp (rpc.messenger.RECEIVED_AT) when this is
        # an RPC task: a burst of frames read in one sweep must measure
        # as near-zero inter-arrival even though their handler tasks
        # run serially behind synchronous work
        t = RECEIVED_AT.get() or time.monotonic()
        if self.last_arrival is not None:
            self.arrival_interval_s.update(max(0.0, t - self.last_arrival))
        self.last_arrival = t

    def retry_after_ms(self) -> int:
        """Backlog drained at the lane's EWMA service rate: how long
        until a retry has a fair shot at admission."""
        per_slot = self.service_ms.value or 1.0
        slots = max(1, self.cfg.workers or 8)
        return int(min(2000.0, max(1.0, self.depth * per_slot / slots)))

    def adaptive_window_s(self, have: int) -> float:
        """Expected time for the group to FILL (recent arrival rate x
        remaining slots), clamped by max_wait — batches grow only when
        traffic is actually arriving; an idle lane never waits.  A
        singleton group earns no window either: one fast SEQUENTIAL
        caller produces the same small inter-arrival EWMA as a
        concurrent fleet, but its next request cannot arrive while it
        is blocked on this one — sleeping would be pure added latency.
        A second member already in the group is the proof of actual
        concurrency."""
        if have < 2 or have >= self.cfg.max_batch \
                or self.cfg.max_wait_us <= 0:
            return 0.0
        iv = self.arrival_interval_s.value
        max_wait = self.cfg.max_wait_us / 1e6
        if iv <= 0.0 or iv > max_wait:
            return 0.0
        return min(iv * (self.cfg.max_batch - have), max_wait)

    def busy(self) -> bool:
        """Arrival-rate gate for the cut-through fast path.  The
        execution engine is largely SYNCHRONOUS on the event loop, so
        an inline dispatch gives concurrent arrivals no await-window in
        which to coalesce — under a fast arrival stream everything
        would degrade to singleton batches.  When requests arrive
        faster than the lane completes them (inter-arrival below the
        EWMA service time — utilization > 1, queueing is inevitable)
        or faster than the floor threshold, they take the queue+worker
        path instead: all arrivals buffered in the same loop sweep then
        join one group before a worker task runs (this deferral IS the
        dynamic part of the micro-batch window)."""
        iv = self.arrival_interval_s.value
        threshold = max(
            flags.get("sched_cut_through_min_interval_us") / 1e6,
            self.service_ms.value / 1e3)
        return 0.0 < iv < threshold


class RequestScheduler:
    """One per tserver. `submit*` either dispatches (through a lane's
    worker pool, possibly batched), sheds with OverloadError, or — when
    the `scheduler_enabled` flag is off — falls straight through to the
    handler (today's direct-dispatch path)."""

    def __init__(self, owner: str,
                 configs: Optional[Dict[Lane, LaneConfig]] = None):
        self.owner = owner
        cfgs = {lane: LaneConfig(**vars(cfg))
                for lane, cfg in DEFAULT_CONFIGS.items()}
        # runtime-flag overrides (tests/ops tune without code changes)
        for lane in Lane:
            cfgs[lane].max_depth = int(flags.get(f"sched_{lane.value}_depth"))
        cfgs[Lane.POINT_READ].max_batch = \
            int(flags.get("sched_read_max_batch"))
        cfgs[Lane.POINT_READ].max_wait_us = \
            int(flags.get("sched_read_max_wait_us"))
        cfgs[Lane.POINT_WRITE].max_batch = \
            int(flags.get("sched_write_max_batch"))
        cfgs[Lane.POINT_WRITE].max_wait_us = \
            int(flags.get("sched_write_max_wait_us"))
        cfgs[Lane.SCAN].max_batch = int(flags.get("sched_scan_max_batch"))
        cfgs[Lane.SCAN].max_wait_us = \
            int(flags.get("sched_scan_max_wait_us"))
        if configs:
            cfgs.update(configs)
        self.lanes: Dict[Lane, _LaneState] = {
            lane: _LaneState(owner, lane, cfg)
            for lane, cfg in cfgs.items()}
        self._workers: List[asyncio.Task] = []
        self._started = False
        self._closed = False

    # --- lifecycle --------------------------------------------------------
    def _ensure_workers(self) -> None:
        if self._started or self._closed:
            return
        self._started = True
        for st in self.lanes.values():
            for i in range(st.cfg.workers or 0):
                self._workers.append(asyncio.create_task(
                    self._worker(st), name=f"sched-{st.lane.value}-{i}"))

    async def shutdown(self) -> None:
        self._closed = True
        # drain_all re-cancels until each worker is really done: a
        # dispatch completing in the cancel's tick can swallow the
        # CancelledError (bpo-37658) and a bare `await t` then hangs
        await drain_all(self._workers)
        self._workers.clear()
        # fail anything still queued so callers don't hang on shutdown
        for st in self.lanes.values():
            pending = list(st.groups.values())
            st.groups.clear()
            while not st.queue.empty():
                pending.append(st.queue.get_nowait())
            for g in pending:
                for _, fut, _, _ in g.items:
                    if not fut.done():
                        fut.set_exception(RpcError("scheduler shut down",
                                                   "SHUTDOWN_IN_PROGRESS"))

    # --- admission --------------------------------------------------------
    def _admit(self, st: _LaneState, cost_bytes: int) -> None:
        if fi.lane_shed_forced(st.lane.value):
            st.m_shed.increment()
            raise OverloadError(
                f"{st.lane.value} lane shedding (fault injection)",
                st.retry_after_ms())
        if st.depth >= st.cfg.max_depth:
            st.m_shed.increment()
            raise OverloadError(
                f"{st.lane.value} lane over depth "
                f"({st.depth}/{st.cfg.max_depth})", st.retry_after_ms())
        if st.queued_bytes + cost_bytes > st.cfg.soft_bytes:
            st.m_shed.increment()
            raise OverloadError(
                f"{st.lane.value} lane over memory soft limit "
                f"({st.queued_bytes >> 20}MB)", st.retry_after_ms())
        st.m_admitted.increment()
        st.note_arrival()

    @staticmethod
    def enabled() -> bool:
        return bool(flags.get("scheduler_enabled"))

    # --- generic (admission-only / unbatched) submission ------------------
    async def submit(self, lane: Lane, run: Callable, *,
                     cost_bytes: int = 1024):
        """Run `run()` under the lane's admission + (for pooled lanes)
        its worker queue.  `run` is an async callable of no args."""
        if not self.enabled():
            return await run()
        if self._closed:
            raise RpcError("scheduler shut down", "SHUTDOWN_IN_PROGRESS")
        st = self.lanes[lane]
        self._admit(st, cost_bytes)
        if st.cfg.workers is None or (
                st.queued == 0 and st.inflight < st.cfg.workers
                and not st.busy() and not fi.lane_armed(st.lane.value)):
            # admission-only lane (TXN class — queueing txn control
            # behind txn control can deadlock), or cut-through on an
            # idle pooled lane: dispatch immediately
            TRACE(f"sched.admit lane={st.lane.value} cut_through")
            st.inflight += 1
            t0 = time.monotonic()
            try:
                return await run()
            finally:
                st.inflight -= 1
                st.service_ms.update((time.monotonic() - t0) * 1e3)
        self._ensure_workers()
        fut = asyncio.get_running_loop().create_future()
        g = _Group(key=object())      # unique key: no batching
        g.items.append((run, fut, cost_bytes, time.monotonic()))
        st.queued += 1
        st.queued_bytes += cost_bytes
        st.m_depth.set(st.depth)
        st.queue.put_nowait(g)
        # the queue span measures admission -> dequeue -> dispatch ->
        # result for THIS request; the worker-side dispatch span (the
        # shared execution) parents under the group's first member
        with TRACES.span(f"sched.queue.{st.lane.value}", child_only=True,
                         tags={"depth": st.depth}):
            with wait_status("SchedQueue_Wait", component="sched"):
                return await fut

    # --- batched submission ----------------------------------------------
    async def submit_grouped(self, lane: Lane, key, payload, *,
                             cost_bytes: int = 1024):
        """Queue `payload` under `key`; payloads sharing a key while
        queued dispatch as ONE group (the lane's executor receives the
        whole group).  Returns this payload's share of the result.

        CUT-THROUGH fast path: when the lane is idle (nothing queued,
        spare worker-equivalent slots) the request dispatches INLINE as
        a singleton group — no queue hop, no future park, zero added
        latency.  Batches form exactly when there is contention to
        amortize (arrivals while work is in flight land in the queue
        and coalesce)."""
        if self._closed:
            raise RpcError("scheduler shut down", "SHUTDOWN_IN_PROGRESS")
        st = self.lanes[lane]
        self._admit(st, cost_bytes)
        now = time.monotonic()
        if st.queued == 0 and st.inflight < (st.cfg.workers or 1) \
                and not st.busy() and not fi.lane_armed(st.lane.value):
            TRACE(f"sched.admit lane={st.lane.value} cut_through")
            st.inflight += 1
            st.m_batch.increment(1)
            st.m_occupancy.increment(100.0 / max(1, st.cfg.max_batch))
            fut = asyncio.get_running_loop().create_future()
            try:
                await self._dispatch_group(
                    st, [(payload, fut, cost_bytes, now)])
                st.service_ms.update((time.monotonic() - now) * 1e3)
                return fut.result()
            finally:
                st.inflight -= 1
        self._ensure_workers()
        fut = asyncio.get_running_loop().create_future()
        g = st.groups.get(key)
        if g is None or g.started or len(g.items) >= st.cfg.max_batch:
            g = _Group(key)
            st.groups[key] = g
            st.queue.put_nowait(g)
        g.items.append((payload, fut, cost_bytes, now))
        st.queued += 1
        st.queued_bytes += cost_bytes
        with TRACES.span(f"sched.queue.{st.lane.value}", child_only=True,
                         tags={"depth": st.depth,
                               "group_members": len(g.items)}):
            with wait_status("SchedQueue_Wait", component="sched"):
                return await fut

    # --- worker loop ------------------------------------------------------
    async def _worker(self, st: _LaneState):
        while True:
            g = await st.queue.get()
            # adaptive micro-batch window: wait only when arrivals are
            # coming fast enough to grow the group, never past max_wait
            # — and never when the lane already has backlog beyond this
            # group (work is waiting NOW; a sleep would cost a whole
            # event-loop sweep and starve it, batches grow via the
            # queue anyway under load)
            try:
                w = (0.0 if st.queued > len(g.items)
                     else st.adaptive_window_s(len(g.items)))
                if w > 0.0:
                    await asyncio.sleep(w)
            except asyncio.CancelledError:
                # cancelled mid-window: the group is off the queue (and
                # may have been replaced under its key once full), so
                # shutdown()'s pending sweep cannot see it — fail its
                # members here or their RPC handlers hang to timeout
                g.started = True
                if st.groups.get(g.key) is g:
                    del st.groups[g.key]
                for _, fut, _, _ in g.items:
                    if not fut.done():
                        fut.set_exception(RpcError(
                            "scheduler shut down", "SHUTDOWN_IN_PROGRESS"))
                raise
            batch = [self._take_group(st, g)]
            # cross-tablet batch fusion: every group already READY in
            # the queue rides THIS wakeup (bounded) and dispatches
            # concurrently below — N same-table groups on different
            # tablets cost one loop sweep + one accounting pass
            # instead of N worker wakeups, and a coalesced device
            # scan's kernel execution overlaps the next group's batch
            # formation (the StreamPipeline stages release the GIL)
            # batched lanes only (max_batch > 1): an admission-
            # serialized lane like MAINTENANCE runs workers=1 exactly
            # so compactions/index builds never overlap — fusing its
            # queue would gather N of them concurrently and break the
            # isolation the lane exists for
            if flags.get("sched_cross_tablet_fusion") \
                    and st.cfg.max_batch > 1:
                cap = int(flags.get("sched_fusion_max_groups"))
                while len(batch) <= cap:
                    try:
                        g2 = st.queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    batch.append(self._take_group(st, g2))
            st.m_fused_wakeup.increment(len(batch))
            if len(batch) == 1:
                await self._run_group(st, batch[0])
            else:
                await asyncio.gather(
                    *[self._run_group(st, items) for items in batch])

    def _take_group(self, st: _LaneState, g: _Group) -> List[tuple]:
        """Synchronous dequeue bookkeeping for one group (no awaits —
        admission must never observe a group as both queued and
        inflight, or neither)."""
        g.started = True
        if st.groups.get(g.key) is g:
            del st.groups[g.key]
        items = g.items
        n = len(items)
        st.queued -= n
        st.queued_bytes -= sum(it[2] for it in items)
        st.inflight += n
        now = time.monotonic()
        for _, _, _, t_in in items:
            st.m_wait.increment((now - t_in) * 1e6)
        st.m_batch.increment(n)
        st.m_occupancy.increment(100.0 * n / max(1, st.cfg.max_batch))
        return items

    async def _run_group(self, st: _LaneState, items: List[tuple]):
        # armed lane stall (fault injection): hold the dispatch —
        # admission keeps running, so tests can fill the queue and
        # observe typed sheds + foreground/background isolation
        try:
            await fi.lane_stall_wait(st.lane.value)
            t0 = time.monotonic()
            await self._dispatch_group(st, items)
            st.service_ms.update((time.monotonic() - t0) * 1e3)
        except asyncio.CancelledError:
            for _, fut, _, _ in items:
                if not fut.done():
                    fut.set_exception(RpcError(
                        "scheduler shut down", "SHUTDOWN_IN_PROGRESS"))
            raise
        except Exception as e:  # noqa: BLE001 — fan the error out
            for _, fut, _, _ in items:
                if not fut.done():
                    fut.set_exception(e)
        finally:
            st.inflight -= len(items)
            st.m_depth.set(st.depth)

    async def _dispatch_group(self, st: _LaneState, items: List[tuple]):
        first = items[0][0]
        if isinstance(first, WriteItem):
            await dispatch_write_group(items, st.m_fanin)
            return
        if isinstance(first, PointReadItem):
            st.m_fanin.increment(len(items))
            await dispatch_point_read_group(items)
            return
        if isinstance(first, ScanItem):
            await dispatch_scan_group(items)
            return
        # generic callable payloads (always singleton groups)
        for payload, fut, _, _ in items:
            res = await payload()
            if not fut.done():
                fut.set_result(res)

    # --- edge admission (messenger overload_probe) ------------------------
    def overload_probe(self, service: str, method: str, payload):
        """Pre-dispatch gate the tserver installs on its messenger: a
        request headed for a lane that is ALREADY past its depth bound
        is shed at the frame edge — no task spawn, no handler — so
        pushback costs a fraction of a served call.  Conservative by
        design: anything it cannot cheaply classify falls through to
        the full admission check in the handler."""
        if service != "tserver" or not self.enabled():
            return None
        try:
            if method == "read":
                lane = classify_read_wire(payload["req"])
            elif method == "write":
                lane = Lane.POINT_WRITE
            elif method == "txn_write":
                lane = Lane.TXN
            else:
                return None
        except (KeyError, TypeError):
            return None
        st = self.lanes[lane]
        if st.depth >= st.cfg.max_depth \
                or fi.lane_shed_forced(st.lane.value):
            st.m_shed.increment()
            return st.retry_after_ms()
        return None

    # --- introspection ----------------------------------------------------
    def stats(self) -> dict:
        """Per-lane live stats for /scheduler, profile_ycsb --json and
        the dashboard."""
        out = {}
        for lane, st in self.lanes.items():
            out[lane.value] = {
                "depth": st.depth,
                "queued": st.queued,
                "inflight": st.inflight,
                "queued_bytes": st.queued_bytes,
                "admitted": st.m_admitted.value(),
                "shed": st.m_shed.value(),
                "service_ms_ewma": round(st.service_ms.value, 3),
                "retry_after_ms": st.retry_after_ms(),
                "wait_us": {
                    "count": st.m_wait.count(),
                    "p50": st.m_wait.percentile(50),
                    "p99": st.m_wait.percentile(99)},
                "batch_size": {
                    "count": st.m_batch.count(),
                    "mean": round(st.m_batch.mean(), 2),
                    "p50": st.m_batch.percentile(50),
                    "max": st.m_batch._max},
                "window_occupancy_pct": {
                    "mean": round(st.m_occupancy.mean(), 1)},
                "group_commit_fanin": {
                    "count": st.m_fanin.count(),
                    "mean": round(st.m_fanin.mean(), 2),
                    "max": st.m_fanin._max},
                "fused_groups_per_wakeup": {
                    "count": st.m_fused_wakeup.count(),
                    "mean": round(st.m_fused_wakeup.mean(), 2),
                    "max": st.m_fused_wakeup._max},
            }
        return out
