"""Priority lanes: classification + per-lane budgets.

Reference analog: the RPC ServicePool priority queues + tablet server
admission gates (src/yb/rpc/service_pool.cc queue limit,
tserver/tablet_server.cc memory-based throttling).  Ours classifies at
the request level so the scheduler can apply per-class queueing,
batching, and shedding policy instead of one FIFO for everything.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional


class Lane(enum.Enum):
    POINT_READ = "point_read"     # pk_eq / pk_prefix lookups
    POINT_WRITE = "point_write"   # plain writes (group-commit eligible)
    SCAN = "scan"                 # scans / aggregate pushdown (coalescible)
    TXN = "txn"                   # txn control + intent writes (never queued
    #                               behind each other: admission-only)
    MAINTENANCE = "maintenance"   # compaction / flush / index builds


@dataclass
class LaneConfig:
    """Budgets for one lane.

    workers None = admission-only: the lane counts in-flight work and
    sheds past its depth, but every admitted request dispatches
    immediately (no worker pool).  Required for the TXN lane — txn
    control ops can transitively depend on EACH OTHER (a conflict wait
    resolves only when another txn's apply/rollback lands), so a
    bounded worker pool could deadlock against itself.
    """
    max_depth: int                 # queued + inflight admission bound
    soft_bytes: int                # memory-based soft limit (estimated)
    workers: Optional[int] = None  # worker-pool size (None = admission-only)
    max_batch: int = 1             # micro-batch cap (1 = no batching)
    max_wait_us: int = 0           # micro-batch window upper bound


# Defaults sized for the in-process cluster: deep enough that normal
# test/bench traffic never sheds, bounded enough that a 2x-saturation
# open loop sheds instead of stacking seconds of queue. Tunable via the
# sched_* runtime flags (utils/flags.py), applied at scheduler
# construction (tserver start).
DEFAULT_CONFIGS = {
    Lane.POINT_READ: LaneConfig(max_depth=512, soft_bytes=64 << 20,
                                workers=16, max_batch=64,
                                max_wait_us=1000),
    Lane.POINT_WRITE: LaneConfig(max_depth=2048, soft_bytes=64 << 20,
                                 workers=4, max_batch=64,
                                 max_wait_us=1000),
    Lane.SCAN: LaneConfig(max_depth=512, soft_bytes=128 << 20,
                          workers=2, max_batch=32, max_wait_us=2000),
    Lane.TXN: LaneConfig(max_depth=4096, soft_bytes=64 << 20,
                         workers=None),
    Lane.MAINTENANCE: LaneConfig(max_depth=64, soft_bytes=256 << 20,
                                 workers=1),
}


def classify_read(req_wire: dict) -> Lane:
    """Lane for a read request (wire dict): full-PK / hash-prefix
    lookups are point reads; everything else (filter scans, aggregate
    pushdown, paged scans) is scan-class work."""
    if req_wire.get("pk_eq") is not None \
            or req_wire.get("pk_prefix") is not None:
        return Lane.POINT_READ
    return Lane.SCAN
