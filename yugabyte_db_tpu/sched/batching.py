"""Micro-batch group semantics: what a coalesced group MEANS.

scheduler.py owns queueing/admission/windows; this module owns the
three batch shapes and their correctness arguments:

- write group commit: same-(tablet, table, schema fence) plain writes
  merge into ONE WriteRequest — one Raft item (one WAL append), one
  tablet apply.  write_id preserves intra-batch order, so the merge is
  observationally the serial execution at one hybrid time; requests
  with external HTs or insert-if-absent ops never enter a group.
- point-read batch: same-(tablet, table) strong point gets share ONE
  leader/lease gate, ONE server-assigned read point (taken after every
  member arrived — each member reads at-or-above its own submit time)
  and ONE engine multi_get (the batched point-read seam YCSB-C
  saturates); per-member projection applied after.
- scan coalesce: same-signature scans execute ONCE — one batched
  kernel launch through the signature-keyed ops/scan.py cache — and
  every waiter receives the response.
"""
from __future__ import annotations

from time import perf_counter as _perf_counter
from typing import List

from ..utils import trace as _trace


class WriteItem:
    """A plain write queued for group commit.  ``tctx`` captures the
    submitter's trace context at construction (the RPC handler's
    context) — the worker task that dispatches the group runs in its
    own context, so the dispatch span bridges back explicitly."""

    __slots__ = ("peer", "req", "tctx")

    def __init__(self, peer, req):
        self.peer = peer
        self.req = req
        self.tctx = _trace.current_context()


class PointReadItem:
    """A strong point get queued for a batched multi_get.  `req_wire`
    is the wire dict (pk_eq set, no pushdown, no explicit read point —
    the tserver checks eligibility before routing here)."""

    __slots__ = ("peer", "req_wire", "tctx")

    def __init__(self, peer, req_wire):
        self.peer = peer
        self.req_wire = req_wire
        self.tctx = _trace.current_context()


class ScanItem:
    """A scan/aggregate read queued for signature coalescing; `run`
    executes it (once per GROUP)."""

    __slots__ = ("run", "tctx")

    def __init__(self, run):
        self.run = run
        self.tctx = _trace.current_context()


async def dispatch_write_group(items: List[tuple], fanin_hist) -> None:
    """GROUP COMMIT: merge the group's ops into one WriteRequest → one
    Raft item + one tablet apply.  Ops keep arrival order, so write_id
    order within the merged batch IS the members' serial order.  The
    merged request rides the peer's write queue, where same-sweep
    requests pack into ONE LogEntry batch, and — with
    ``fused_replicate_enabled`` — concurrent entries (other tables,
    txn ops) further fuse into one WAL append + one replicate round
    (the ReplicateBatch shape)."""
    from ..docdb.operations import WriteRequest
    from ..tablet.tablet_peer import WRITE_PATH_STATS
    t0 = _perf_counter()
    first = items[0][0]
    ops = []
    for wb, _, _, _ in items:
        ops.extend(wb.req.ops)
    merged = WriteRequest(first.req.table_id, ops,
                          schema_version=first.req.schema_version)
    fanin_hist.increment(len(items))
    WRITE_PATH_STATS["group_merge_s"] += _perf_counter() - t0
    # dispatch span parents under the FIRST member's request (the
    # worker task has no ambient context of its own); fanin tags how
    # many requests shared this one WAL append + apply
    with _trace.use_context(first.tctx):
        with _trace.TRACES.span("sched.dispatch.write", child_only=True,
                                tags={"fanin": len(items)}):
            await first.peer.write(merged)
    for wb, fut, _, _ in items:
        if not fut.done():
            fut.set_result({"rows_affected": len(wb.req.ops)})


async def dispatch_point_read_group(items: List[tuple]) -> None:
    """Batched point gets: one gate + read point + safe-time wait +
    multi_get for the whole group; per-member wire responses built
    through the SAME response codec as the unbatched path (byte
    parity is pinned by tests/test_scheduler.py)."""
    from ..docdb.operations import ReadResponse
    from ..docdb.wire import read_response_to_wire
    first = items[0][0]
    table_id = first.req_wire["table_id"]
    pk_rows = [it[0].req_wire["pk_eq"] for it in items]
    with _trace.use_context(first.tctx):
        with _trace.TRACES.span("sched.dispatch.point_read",
                                child_only=True,
                                tags={"fanin": len(items)}):
            rows = await first.peer.read_points(table_id, pk_rows)
    for (pr, fut, _, _), row in zip(items, rows):
        cols = tuple(pr.req_wire.get("columns") or ())
        if row is not None and cols:
            row = {c: row.get(c) for c in cols}   # _project twin
        resp = ReadResponse(rows=[row] if row is not None else [],
                            backend="cpu")
        if not fut.done():
            fut.set_result(read_response_to_wire(resp))


async def dispatch_scan_group(items: List[tuple]) -> None:
    """Same-signature scans: ONE execution, response fanned out.  The
    read point resolves at dispatch — AFTER every member arrived — so
    coalescing never serves a member data older than its own arrival;
    explicit read points are part of the signature (identical
    snapshot only)."""
    sb = items[0][0]
    with _trace.use_context(sb.tctx):
        with _trace.TRACES.span("sched.dispatch.scan", child_only=True,
                                tags={"fanin": len(items)}):
            resp = await sb.run()
    for _, fut, _, _ in items:
        if not fut.done():
            # top-level copy per waiter: local short-circuit callers
            # must not see each other's mutations of the envelope
            fut.set_result(dict(resp))
