"""Admission-controlled request scheduler with dynamic micro-batching.

The marshalling layer between RPC dispatch and tablet execution
(Tailwind's framing: the accelerator boundary is a batching problem —
work must arrive in accelerator-friendly chunks to amortize launch
cost).  Three pieces:

- lanes.py: classification of inbound work into priority lanes
  (point read / point write / scan / txn / maintenance) with per-lane
  depth and memory budgets.
- scheduler.py: bounded admission (typed ServiceUnavailable +
  retry_after_ms on overload instead of latency collapse), per-lane
  worker pools, and dynamic micro-batch windows that coalesce
  same-tablet point writes into one WAL append + one tablet apply
  (group commit) and same-signature scans into one kernel launch
  through the ops/scan.py signature-keyed kernel cache.

The tserver routes its data-path RPCs through here when the
`scheduler_enabled` runtime flag is on; flag off reverts to the
direct-dispatch path.
"""
from .batching import PointReadItem, ScanItem, WriteItem
from .lanes import Lane, LaneConfig, classify_read
from .scheduler import OverloadError, RequestScheduler, canon

__all__ = ["Lane", "LaneConfig", "OverloadError", "PointReadItem",
           "RequestScheduler", "ScanItem", "WriteItem", "canon",
           "classify_read"]
