"""Profile the multi-process cluster harness.

`--json` prints ONE JSON object timing the harness's own moving parts
— process spawn → READY latency per role, driver setup/load rate,
closed-loop saturation, one open-loop phase at 1x and 2x with the
latency split, graceful-drain wall (SIGTERM → exit 0) vs
kill+restart-to-READY wall, and a cross-process control-RPC
round-trip cost (`metrics_snapshot` / `arm_fault`) — so harness
overhead is separable from the database behavior it measures
(a supervisor that takes 4s to notice READY would silently eat the
chaos round's restart budget).

Env knobs: PROFILE_CLUSTER_TSERVERS (default 2), PROFILE_CLUSTER_ROWS
(default 500), PROFILE_CLUSTER_PHASE_S (default 1.5).
"""
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("YBTPU_PLATFORM", "cpu")
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def profile_json() -> dict:
    import asyncio

    from yugabyte_db_tpu.cluster import ClusterSupervisor

    n_ts = int(os.environ.get("PROFILE_CLUSTER_TSERVERS", "2"))
    rows = int(os.environ.get("PROFILE_CLUSTER_ROWS", "500"))
    phase_s = float(os.environ.get("PROFILE_CLUSTER_PHASE_S", "1.5"))

    async def run():
        out = {"num_tservers": n_ts, "rows": rows, "phase_s": phase_s}
        sup = ClusterSupervisor(
            tempfile.mkdtemp(prefix="ybtpu-profcl-"),
            num_tservers=0)
        t0 = time.perf_counter()
        await sup.start()                      # master only
        out["master_ready_s"] = round(time.perf_counter() - t0, 3)
        try:
            spawns = []
            for i in range(n_ts):
                t0 = time.perf_counter()
                await sup.spawn_tserver(i)
                spawns.append(round(time.perf_counter() - t0, 3))
            await sup.wait_tservers_live()
            out["tserver_ready_s"] = spawns

            t0 = time.perf_counter()
            await sup.spawn_driver("drv-0")
            out["driver_ready_s"] = round(time.perf_counter() - t0, 3)

            t0 = time.perf_counter()
            await sup.call("drv-0", "driver", "setup",
                           {"rows": rows, "num_tablets": 2,
                            "replication_factor": min(2, max(1, n_ts))},
                           timeout=120.0)
            load_s = time.perf_counter() - t0
            out["setup_s"] = round(load_s, 3)
            out["load_rows_per_s"] = round(rows / max(load_s, 1e-9), 1)

            # control-RPC round-trip cost (the supervisor's assertion
            # surface — it rides inside every bench/chaos loop)
            for method, payload in (("metrics_snapshot", {}),
                                    ("fault_status", {})):
                t0 = time.perf_counter()
                for _ in range(20):
                    await sup.call("ts-0", "tserver", method, payload,
                                   timeout=10.0)
                out[f"{method}_rtt_ms"] = round(
                    (time.perf_counter() - t0) / 20 * 1e3, 2)

            sat = (await sup.call("drv-0", "driver", "saturation",
                                  {"seconds": phase_s, "workers": 32},
                                  timeout=60.0))["ops_per_s"]
            out["saturation_ops_per_s"] = round(sat, 1)
            for label, mult in (("phase_1x", 1.0), ("phase_2x", 2.0)):
                out[label] = await sup.call(
                    "drv-0", "driver", "run_phase",
                    {"rate": min(mult * sat, 4000.0),
                     "seconds": phase_s, "sla_ms": 2000,
                     "tag": label}, timeout=120.0)

            # --- trace_overhead: paired sampled-on/off phases --------
            # the ISSUE 14 overhead gate across REAL processes: the
            # same 1x phase with trace_sampling_rate=0 vs the default
            # on every tserver (the ASH sampler thread always runs in
            # server_main), interleaved, best-of.  WARN at >2% cost.
            from yugabyte_db_tpu.utils import flags as _flags
            default_rate = _flags.REGISTRY._flags[
                "trace_sampling_rate"].default
            t_res = {"off": [], "on": []}
            for i in range(2):
                for side, rate in (("off", 0.0), ("on", default_rate)):
                    await sup.set_flag_all("trace_sampling_rate", rate,
                                           roles=("tserver",))
                    ph = await sup.call(
                        "drv-0", "driver", "run_phase",
                        {"rate": min(sat, 4000.0), "seconds": phase_s,
                         "sla_ms": 2000, "tag": f"trace-{side}{i}"},
                        timeout=120.0)
                    t_res[side].append(ph["achieved_ops_per_s"])
            await sup.set_flag_all("trace_sampling_rate", default_rate,
                                   roles=("tserver",))
            ratio = round(max(t_res["on"])
                          / max(max(t_res["off"]), 1e-9), 3)
            out["trace_overhead"] = {
                "default_sampling_rate": default_rate,
                "achieved_ops_per_s_off": round(max(t_res["off"]), 1),
                "achieved_ops_per_s_on": round(max(t_res["on"]), 1),
                "on_vs_off": ratio,
            }
            if ratio < 0.98:
                print(f"WARN: cluster trace_overhead on_vs_off={ratio} "
                      "— tracing at default sampling costs >2% of "
                      "cluster goodput", file=sys.stderr)

            # drain vs crash-restart walls
            t0 = time.perf_counter()
            code = await sup.stop("ts-0", drain=True)
            out["drain_s"] = round(time.perf_counter() - t0, 3)
            out["drain_exit_code"] = code
            t0 = time.perf_counter()
            await sup.restart("ts-0")
            out["restart_after_drain_s"] = round(
                time.perf_counter() - t0, 3)
            await sup.kill("ts-0")
            t0 = time.perf_counter()
            await sup.restart("ts-0")
            out["restart_after_kill_s"] = round(
                time.perf_counter() - t0, 3)
            return out
        finally:
            await sup.shutdown()

    return asyncio.run(run())


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    out = profile_json()
    if "--json" in args:
        print(json.dumps(out))
    else:
        for k, v in out.items():
            print(f"{k}: {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
