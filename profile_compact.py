"""Profile device vs native compaction (throwaway)."""
import os, tempfile, time
os.environ.setdefault("YBTPU_PLATFORM", "cpu")
import numpy as np
from yugabyte_db_tpu.models.tpch import generate_lineitem, LineitemTable
from yugabyte_db_tpu.utils.hybrid_time import HybridTime
from yugabyte_db_tpu.utils import flags

data = generate_lineitem(float(os.environ.get("BENCH_SF", "1.0")))
n = len(data["rowid"])
n_ssts = int(os.environ.get("N_SSTS", "100"))
rows_per = int(os.environ.get("ROWS_PER", "20000"))


def make(tag):
    t = LineitemTable(tempfile.mkdtemp(prefix=f"comp-{tag}-"),
                      num_tablets=1).tablets[0]
    base_us = int(time.time() * 1e6)
    for i in range(n_ssts):
        fresh = (i * rows_per) % max(n - rows_per, 1)
        sel = np.arange(fresh, fresh + rows_per) % n
        if i > 0:
            prev = (sel - rows_per // 4) % n
            sel[: rows_per // 4] = prev[: rows_per // 4]
        batch = {k: v[sel] for k, v in data.items()}
        t.bulk_load(batch, ht=HybridTime.from_micros(base_us + i * 1000))
    return t

for backend, flag in (("device", True), ("native", False)):
    t = make(backend)
    total = t.approximate_size()
    flags.set_flag("tpu_compaction_enabled", flag)
    t0 = time.perf_counter()
    t.compact()
    dt = time.perf_counter() - t0
    print(f"{backend}: {total/1e6:.1f} MB in {dt:.2f}s = "
          f"{total/1e6/dt:.1f} MB/s")
flags.REGISTRY.reset("tpu_compaction_enabled")

# phase breakdown for the device path
import cProfile, pstats
t = make("prof")
flags.set_flag("tpu_compaction_enabled", True)
pr = cProfile.Profile(); pr.enable()
t.compact()
pr.disable()
pstats.Stats(pr).sort_stats("cumulative").print_stats(18)
