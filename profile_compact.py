"""Profile the compaction engine (pipelined chunked vs monolithic CPU).

Default: human-readable backend comparison + cProfile phase breakdown.
--json: one JSON object on stdout with
  - backends: MB/s per backend (pipelined native, monolithic baseline)
  - chunk_sweep: MB/s + pipeline stage timings per frontier budget
  - kernel_cache: merge-kernel compile counts for a first and a
    same-shape second device-backend compaction (shape-stable caching
    means the second must report 0 compiles)
Env knobs: BENCH_SF (default 1.0), N_SSTS (default 100), ROWS_PER
(default 20000), PROFILE_CHUNK_SWEEP (comma-separated row budgets).
"""
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("YBTPU_PLATFORM", "cpu")

import numpy as np

from yugabyte_db_tpu.models.tpch import generate_lineitem, LineitemTable
from yugabyte_db_tpu.utils.hybrid_time import HybridTime
from yugabyte_db_tpu.utils import flags

data = generate_lineitem(float(os.environ.get("BENCH_SF", "1.0")))
n = len(data["rowid"])
n_ssts = int(os.environ.get("N_SSTS", "100"))
rows_per = int(os.environ.get("ROWS_PER", "20000"))
as_json = "--json" in sys.argv


def make(tag):
    t = LineitemTable(tempfile.mkdtemp(prefix=f"comp-{tag}-"),
                      num_tablets=1).tablets[0]
    base_us = int(time.time() * 1e6)
    for i in range(n_ssts):
        fresh = (i * rows_per) % max(n - rows_per, 1)
        sel = np.arange(fresh, fresh + rows_per) % n
        if i > 0:
            prev = (sel - rows_per // 4) % n
            sel[: rows_per // 4] = prev[: rows_per // 4]
        batch = {k: v[sel] for k, v in data.items()}
        t.bulk_load(batch, ht=HybridTime.from_micros(base_us + i * 1000))
    return t


def timed_compact(flag):
    # the baseline side runs the full pre-PR world: monolithic engine
    # AND sst_format_version=1 (inputs and output), so its output is
    # the v1 byte yardstick for v2_vs_v1_bytes
    if not flag:
        flags.set_flag("sst_format_version", 1)
    try:
        t = make("dev" if flag else "cpu")
        total = t.approximate_size()
        flags.set_flag("tpu_compaction_enabled", flag)
        t0 = time.perf_counter()
        t.compact()
        dt = time.perf_counter() - t0
    finally:
        if not flag:
            flags.REGISTRY.reset("sst_format_version")
    out = t.regular.ssts[0]
    return total, dt, out.file_size, out.num_entries


from yugabyte_db_tpu.docdb.compaction import (LAST_COMPACTION_STATS,
                                              tpu_compact)

if as_json:
    out = {"n_ssts": n_ssts, "rows_per_sst": rows_per,
           "rows": n_ssts * rows_per}
    # backend comparison (same harness as bench.py config 4)
    out["backends"] = {}
    for name, flag in (("pipelined_native", True), ("baseline", False)):
        total, dt, out_bytes, out_rows = timed_compact(flag)
        out["backends"][name] = {
            "mb": round(total / 1e6, 1), "seconds": round(dt, 3),
            "mb_per_s": round(total / 1e6 / dt, 1),
            # the baseline backend writes the pre-v2 (v1) format, so
            # these two entries ARE the per-format byte comparison
            "output_bytes": out_bytes, "output_rows": out_rows,
            "output_bytes_per_row": round(out_bytes / max(out_rows, 1),
                                          2)}
        if flag:
            s = dict(LAST_COMPACTION_STATS)
            lanes = s.pop("lanes", {})
            out["backends"][name]["pipeline"] = {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in s.items()}
            # per-lane encoded-size breakdown: encoding chosen +
            # pre/post bytes, so the v2 win is attributable per lane
            out["backends"][name]["lanes"] = {
                ln: {"pre_bytes": e["pre_bytes"],
                     "post_bytes": e["post_bytes"],
                     "ratio": round(e["post_bytes"]
                                    / max(e["pre_bytes"], 1), 3),
                     "encodings": e["encodings"]}
                for ln, e in sorted(lanes.items())}
    v1b = out["backends"]["baseline"]["output_bytes"]
    v2b = out["backends"]["pipelined_native"]["output_bytes"]
    out["v2_vs_v1_bytes"] = round(v1b / max(v2b, 1), 3)
    flags.REGISTRY.reset("tpu_compaction_enabled")
    # chunk-size sweep over the pipelined engine
    sweep_env = os.environ.get("PROFILE_CHUNK_SWEEP", "131072,262144,524288")
    out["chunk_sweep"] = []
    flags.set_flag("tpu_compaction_enabled", True)
    for chunk in (int(x) for x in sweep_env.split(",") if x.strip()):
        flags.set_flag("compaction_chunk_rows", chunk)
        total, dt, _ob, _or = timed_compact(True)
        s = dict(LAST_COMPACTION_STATS)
        out["chunk_sweep"].append({
            "chunk_rows": chunk, "mb_per_s": round(total / 1e6 / dt, 1),
            "chunks": s.get("chunks"),
            "frontier_rows": s.get("frontier_rows"),
            "emitted_rows": s.get("emitted_rows"),
            "stage_s": {k: round(s.get(k, 0.0), 4)
                        for k in ("decode_wait_s", "merge_wait_s",
                                  "gather_s", "write_wait_s")},
            # fused gather/encode accounting: one GIL-released native
            # call should carry ~all jobs; fallback_calls > 0 means a
            # column shape fell back to per-column numpy gathers
            "gather": {k: s.get(k) for k in
                       ("fused_gather_calls", "fused_gather_jobs",
                        "gather_fallback_calls")}})
    flags.REGISTRY.reset("compaction_chunk_rows")
    flags.REGISTRY.reset("tpu_compaction_enabled")
    # kernel-cache behavior: two same-shape device-backend compactions.
    # Shape-stable bucketing means the first compiles at most a few
    # signatures and the second compiles none.
    kc = {}
    for run in ("first", "second"):
        t = make(f"kc-{run}")
        tpu_compact(t.regular, t.codec, t.history_cutoff(),
                    backend="device")
        s = dict(LAST_COMPACTION_STATS)
        kc[run] = {"kernel_compiles": s.get("kernel_compiles"),
                   "kernel_calls": s.get("kernel_calls"),
                   "kernel_cache_hits": s.get("kernel_cache_hits"),
                   "chunks": s.get("chunks")}
    out["kernel_cache"] = kc
    print(json.dumps(out))
else:
    for backend, flag in (("device", True), ("native", False)):
        total, dt, ob, orows = timed_compact(flag)
        print(f"{backend}: {total/1e6:.1f} MB in {dt:.2f}s = "
              f"{total/1e6/dt:.1f} MB/s  "
              f"(out {ob/max(orows,1):.1f} B/row)")
    flags.REGISTRY.reset("tpu_compaction_enabled")

    # phase breakdown for the pipelined path
    import cProfile
    import pstats
    t = make("prof")
    flags.set_flag("tpu_compaction_enabled", True)
    pr = cProfile.Profile()
    pr.enable()
    t.compact()
    pr.disable()
    flags.REGISTRY.reset("tpu_compaction_enabled")
    pstats.Stats(pr).sort_stats("cumulative").print_stats(18)
