#!/usr/bin/env python
"""Fast on-device validation: the <5-minute TPU fire drill.

The axon tunnel comes and goes (round 2 proved windows can be as short
as ~20 minutes).  This script is the first thing to run the moment a
device appears: it re-validates the exact-int64 SUM contract and times
the flagship kernels (Q6 / Q1 / compaction / vector / YCSB-C) at
reduced scale, then appends a timestamped section to TPU_RESULTS.md so
on-device evidence survives even if the window closes before the full
`bench.py` finishes.

Run directly (`python tpu_smoke.py`) or via tools/tpu_probe_loop.sh
which fires it automatically when a probe succeeds.  Exit codes:
0 = ran on a real accelerator, all checks passed; 2 = no device
(nothing recorded); 1 = device present but a check FAILED (recorded).

Env: SMOKE_SKIP_PROBE=1 trusts the caller's probe (the loop probed
seconds earlier; first-contact jax init over the tunnel can take
minutes, which would burn a short window twice).  SMOKE_ALLOW_CPU=1 +
YBTPU_PLATFORM=cpu exercises the body on the host platform for testing
(no TPU_RESULTS.md append).  SMOKE_SF / SMOKE_COMPACT_SSTS /
SMOKE_COMPACT_ROWS scale the work.

Reference for what must stay exact: PG aggregate semantics in
/root/reference/src/yb/docdb/pgsql_operation.cc:3153 (EvalAggregate).
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

from bench import best_of, probe_device

_TMPDIRS = []


def _mkdtemp(prefix):
    d = tempfile.mkdtemp(prefix=prefix)
    _TMPDIRS.append(d)
    return d


def probe():
    """Real-accelerator probe via bench.probe_device (shared subprocess
    machinery — a wedged tunnel hangs jax.devices forever).  Unlike the
    bench, a CPU-only answer is a FAILURE here: the smoke's entire
    purpose is on-device evidence.

    Note: env JAX_PLATFORMS=cpu does NOT prevent the axon plugin from
    wedging at import — only jax.config.update pre-init does (see
    tests/conftest.py) — so the CPU test path (SMOKE_ALLOW_CPU=1 +
    YBTPU_PLATFORM=cpu) skips the probe entirely; the package __init__
    applies the config-level override."""
    if os.environ.get("SMOKE_ALLOW_CPU") == "1":
        return "cpu-forced (test mode)"
    if os.environ.get("SMOKE_SKIP_PROBE") == "1":
        return "probe skipped (caller verified)"
    ok, attempts = probe_device(timeouts=(90, 240))
    if not ok:
        return None
    dev = attempts[-1].get("device", "")
    if "cpu" in dev.lower():
        return None  # host platform only: not a real window
    return dev


def main():
    t_start = time.time()
    dev_str = probe()
    if dev_str is None:
        print(json.dumps({"ok": False, "reason": "no accelerator"}))
        return 2

    import numpy as np
    import jax

    from yugabyte_db_tpu.models.tpch import (
        LineitemTable, TPCH_Q1, TPCH_Q6, generate_lineitem, numpy_reference,
    )
    from yugabyte_db_tpu.ops.cpu_scan import cpu_scan_aggregate
    from yugabyte_db_tpu.ops.device_batch import build_batch
    from yugabyte_db_tpu.ops.scan import ScanKernel
    from yugabyte_db_tpu.utils import flags
    from yugabyte_db_tpu.utils.hybrid_time import HybridTime

    dev = jax.devices()[0]
    res = {"device": str(dev), "probe": dev_str}
    failures = []
    sum_contract_failures = []   # the exact-int64 qty checks specifically

    # ---- 1. exact-SUM contract at scale (>2^24 per-group sums) --------
    # integer-valued f64 column summed through the device kernel must be
    # EXACT (int64 fixed-point accumulation, host-derived static scales)
    sf = float(os.environ.get("SMOKE_SF", "0.2"))
    data = generate_lineitem(sf)
    n = len(data["rowid"])
    table = LineitemTable(_mkdtemp("ybtpu-smoke-"), num_tablets=1)
    table.load(data)
    tablet = table.tablets[0]
    blocks = []
    for r in tablet.regular.ssts:
        for i in range(r.num_blocks()):
            blocks.append(r.columnar_block(i))

    kernel = ScanKernel()
    for q in (TPCH_Q6, TPCH_Q1):
        batch = build_batch(blocks, sorted(q.columns))

        def run():
            outs, counts, _ = kernel.run(batch, q.where, q.aggs, q.group)
            jax.block_until_ready(outs)
            return outs, counts
        run()  # compile
        t_dev, (outs, counts) = best_of(run, 3)
        t_cpu, _ = best_of(
            lambda: cpu_scan_aggregate(blocks, q.columns, q.where,
                                       q.aggs, q.group), 2)
        ref = numpy_reference(q, data)
        if q.name == "q6":
            rel = abs(float(outs[0]) - ref) / max(abs(ref), 1e-9)
            if rel >= 1e-5:
                failures.append(f"q6 rel err {rel:.2e}")
        else:
            sums = [np.asarray(o) for o in outs]
            cts = np.asarray(counts)
            for g in range(6):
                want_qty, want_price, want_cnt = ref[g]
                if int(cts[g]) != want_cnt:
                    failures.append(f"q1 g{g} count {int(cts[g])}"
                                    f" != {want_cnt}")
                # qty is integer-valued: must be EXACT on device
                if abs(float(sums[0][g]) - want_qty) > 1e-9 * max(
                        abs(want_qty), 1):
                    sum_contract_failures.append(
                        f"q1 g{g} qty {float(sums[0][g])} != {want_qty}"
                        " (exact-SUM contract violated)")
                relp = abs(float(sums[1][g]) - want_price) / max(
                    want_price, 1e-9)
                if relp >= 1e-5:
                    failures.append(f"q1 g{g} price rel {relp:.2e}")
        res[q.name] = {"dev_s": round(t_dev, 5), "cpu_s": round(t_cpu, 5),
                       "rows_per_s": round(n / t_dev, 1),
                       "speedup": round(t_cpu / t_dev, 2)}
    failures.extend(sum_contract_failures)

    # ---- 2. compaction: device merge vs native CPU feed ----------------
    n_ssts = int(os.environ.get("SMOKE_COMPACT_SSTS", "20"))
    rows_per = int(os.environ.get("SMOKE_COMPACT_ROWS", "10000"))

    def make_tablet(tag):
        t = LineitemTable(_mkdtemp(f"smoke-c-{tag}-"),
                          num_tablets=1).tablets[0]
        base_us = int(time.time() * 1e6)
        for i in range(n_ssts):
            fresh = (i * rows_per) % max(n - rows_per, 1)
            sel = np.arange(fresh, fresh + rows_per) % n
            if i > 0:
                prev = (sel - rows_per // 4) % n
                sel[: rows_per // 4] = prev[: rows_per // 4]
            batch = {k: v[sel] for k, v in data.items()}
            t.bulk_load(batch, ht=HybridTime.from_micros(base_us + i * 1000))
        return t

    comp = {}
    for flag, tag in ((True, "dev"), (False, "cpu")):
        ct = make_tablet(tag)
        nbytes = ct.approximate_size()
        flags.set_flag("tpu_compaction_enabled", flag)
        t0 = time.perf_counter()
        ct.compact()
        comp[tag] = time.perf_counter() - t0
        comp.setdefault("mb", nbytes / 1e6)
    flags.set_flag("tpu_compaction_enabled", True)
    res["compaction"] = {"ssts": n_ssts, "input_mb": round(comp["mb"], 1),
                         "dev_s": round(comp["dev"], 3),
                         "cpu_s": round(comp["cpu"], 3),
                         "vs_cpu": round(comp["cpu"] / comp["dev"], 3)}

    # ---- 3. vector search (reduced config) -----------------------------
    from yugabyte_db_tpu.ops.vector import IvfFlatIndex
    rngv = np.random.default_rng(0)
    vbase = rngv.normal(size=(200_000, 128)).astype(np.float32)
    t0 = time.perf_counter()
    idx = IvfFlatIndex.build(vbase, nlists=64, iters=3, sample=50_000)
    build_s = time.perf_counter() - t0
    vq = vbase[:64] + 0.001
    idx.search(vq, k=10, nprobe=8)  # compile
    t_s, _ = best_of(lambda: idx.search(vq, k=10, nprobe=8), 3)
    res["vector"] = {"n": 200_000, "dim": 128,
                     "build_s": round(build_s, 2),
                     "qps": round(64 / t_s, 1)}

    # ---- 4. YCSB-C quick point reads -----------------------------------
    from yugabyte_db_tpu.models.ycsb import YcsbTabletWorkload, \
        usertable_info
    from yugabyte_db_tpu.tablet import Tablet
    yt = Tablet("ycsb", usertable_info(), _mkdtemp("smoke-ycsb-"))
    w = YcsbTabletWorkload(yt, n_rows=50_000)
    w.load()
    w.run("c", ops=1000)  # warm
    rc = w.run("c", ops=5000)
    res["ycsb_c"] = {"ops_per_s": round(rc.ops_per_sec, 1)}

    res["ok"] = not failures
    if failures:
        res["failures"] = failures
    res["total_s"] = round(time.time() - t_start, 1)

    for d in _TMPDIRS:
        shutil.rmtree(d, ignore_errors=True)

    # ---- append to TPU_RESULTS.md (real-device runs only) --------------
    if os.environ.get("SMOKE_ALLOW_CPU") == "1":
        print(json.dumps(res))
        return 0 if res["ok"] else 1
    head = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                          capture_output=True, cwd=os.path.dirname(
                              os.path.abspath(__file__)))
    head = (head.stdout or b"?").decode().strip()
    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    sum_label = ("EXACT" if not sum_contract_failures else
                 "VIOLATED: " + "; ".join(sum_contract_failures))
    md = (f"\n## tpu_smoke.py run — {stamp} (HEAD {head})\n\n"
          f"Device: `{res['device']}` — "
          f"{'ALL CHECKS PASSED' if res['ok'] else 'FAILURES: ' + '; '.join(failures)}\n\n"
          f"| metric | device | cpu | ratio |\n|---|---|---|---|\n"
          f"| Q6 SF={sf} | {res['q6']['dev_s']}s "
          f"({res['q6']['rows_per_s']:.3g} rows/s) | {res['q6']['cpu_s']}s"
          f" | **{res['q6']['speedup']}x** |\n"
          f"| Q1 SF={sf} | {res['q1']['dev_s']}s "
          f"({res['q1']['rows_per_s']:.3g} rows/s) | {res['q1']['cpu_s']}s"
          f" | **{res['q1']['speedup']}x** |\n"
          f"| compaction {n_ssts} SSTs ({res['compaction']['input_mb']}MB)"
          f" | {res['compaction']['dev_s']}s | {res['compaction']['cpu_s']}s"
          f" | **{res['compaction']['vs_cpu']}x** |\n"
          f"| vector 200K-128 search | {res['vector']['qps']} qps | - | - |\n"
          f"| YCSB-C 5K ops | {res['ycsb_c']['ops_per_s']} ops/s | - | - |\n"
          f"\nExact-int64 SUM contract (Q1 qty at SF={sf}): {sum_label}; "
          f"total smoke time {res['total_s']}s.\n")
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TPU_RESULTS.md")
    with open(path, "a") as f:
        f.write(md)

    print(json.dumps(res))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
