#!/usr/bin/env python
"""Static pass: blocking calls inside `async def` bodies.

The request scheduler (yugabyte_db_tpu/sched/) multiplexes every lane's
dispatch over the one event loop, so a synchronous stall inside an
async handler no longer slows one RPC — it freezes admission, batching
windows, Raft heartbeats and lease renewal for the whole server.  This
pass flags the classic offenders lexically inside `async def` bodies:

- time.sleep(...)          (use asyncio.sleep)
- open(...)                (sync file I/O; use run_in_executor for
                            anything non-trivial)
- os.fsync(...)            (device stall on the loop)

Scope: yugabyte_db_tpu/tserver/ and yugabyte_db_tpu/rpc/ — the two
packages on the scheduler's dispatch path.  Nested (non-async) `def`
bodies are NOT flagged: they are frequently executor targets.

A finding is suppressed when its line (or the line above) carries a
`blocking-ok: <reason>` comment — the annotation documents WHY the
stall is acceptable (tiny metadata file, bounded chunk, ...) and makes
new unannotated stalls a test failure (tests/test_check_blocking.py
wires this into tier-1).

Usage: python tools/check_blocking.py [path ...]; exits 1 on findings.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

ALLOW_MARK = "blocking-ok"

DEFAULT_ROOTS = ("yugabyte_db_tpu/tserver", "yugabyte_db_tpu/rpc")


def _call_name(node: ast.Call) -> str:
    """Dotted name of a call target ('time.sleep', 'open', ...)."""
    f = node.func
    parts: List[str] = []
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return ".".join(reversed(parts))


BLOCKING = {"time.sleep", "open", "os.fsync"}


class _AsyncBodyScanner(ast.NodeVisitor):
    """Collect blocking calls lexically inside async def bodies,
    stopping at nested function definitions (sync helpers are often
    executor targets; nested async defs get their own visit)."""

    def __init__(self):
        self.findings: List[Tuple[int, str]] = []

    def visit_AsyncFunctionDef(self, node):
        for stmt in node.body:
            self._scan(stmt)
        # nested async defs are scanned when _scan reaches them

    def _scan(self, node):
        if isinstance(node, (ast.FunctionDef, ast.Lambda)):
            return                      # executor-target territory
        if isinstance(node, ast.AsyncFunctionDef):
            self.visit_AsyncFunctionDef(node)
            return
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name in BLOCKING:
                self.findings.append((node.lineno, name))
        for child in ast.iter_child_nodes(node):
            self._scan(child)


def scan_file(path: str) -> List[Tuple[str, int, str]]:
    with open(path) as f:
        src = f.read()
    lines = src.splitlines()
    scanner = _AsyncBodyScanner()
    scanner.visit(ast.parse(src, filename=path))
    out = []
    for lineno, name in scanner.findings:
        here = lines[lineno - 1] if lineno <= len(lines) else ""
        above = lines[lineno - 2] if lineno >= 2 else ""
        if ALLOW_MARK in here or ALLOW_MARK in above:
            continue
        out.append((path, lineno, name))
    return out


def scan(roots=DEFAULT_ROOTS, base: str = ".") -> List[Tuple[str, int, str]]:
    findings = []
    for root in roots:
        rootp = os.path.join(base, root)
        for dirpath, _dirs, files in os.walk(rootp):
            for fn in sorted(files):
                if fn.endswith(".py"):
                    findings.extend(scan_file(os.path.join(dirpath, fn)))
    return findings


def main(argv) -> int:
    base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    roots = argv[1:] or DEFAULT_ROOTS
    findings = scan(roots, base)
    for path, lineno, name in findings:
        rel = os.path.relpath(path, base)
        print(f"{rel}:{lineno}: blocking call `{name}` inside async def "
              f"(annotate `# {ALLOW_MARK}: <reason>` if the stall is "
              f"genuinely bounded)")
    if findings:
        print(f"{len(findings)} blocking call(s) found")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
