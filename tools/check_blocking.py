#!/usr/bin/env python
"""Thin compatibility shim over tools/analyze/ (the framework owns the
pass now — see ANALYSIS.md).

Historically this file WAS the blocking-call lint: time.sleep / open /
os.fsync inside ``async def`` bodies of tserver/ + rpc/.  The pass
lives on as ``analyze.passes.async_blocking`` with a wider offender set
and whole-tree scope; this shim keeps the old CLI and the old
``scan()`` contract (``[(path, lineno, dotted_name)]``, default roots
tserver/ + rpc/) so tests/test_check_blocking.py and any muscle-memory
invocations keep working, and `blocking-ok:` annotations stay honored
(the framework treats them as an alias of
``analysis-ok(async_blocking)``).

Usage: python tools/check_blocking.py [path ...]; exits 1 on findings.
"""
from __future__ import annotations

import os
import sys
from typing import List, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)

from analyze import ProjectIndex, run_analysis  # noqa: E402
from analyze.passes.async_blocking import PASS as _PASS  # noqa: E402

ALLOW_MARK = "blocking-ok"

DEFAULT_ROOTS = ("yugabyte_db_tpu/tserver", "yugabyte_db_tpu/rpc")


def scan(roots=DEFAULT_ROOTS, base: str = ".") -> List[Tuple[str, int, str]]:
    index = ProjectIndex(base, roots=roots)
    report = run_analysis(index, [_PASS])
    return [(os.path.join(index.base, f["path"]), f["line"], f["detail"])
            for f in report["findings"]]


def scan_file(path: str) -> List[Tuple[str, int, str]]:
    base = os.path.dirname(os.path.abspath(path)) or "."
    return scan(roots=(os.path.basename(path),), base=base)


def main(argv) -> int:
    base = os.path.dirname(_HERE)
    roots = argv[1:] or DEFAULT_ROOTS
    findings = scan(roots, base)
    for path, lineno, name in findings:
        rel = os.path.relpath(path, base)
        print(f"{rel}:{lineno}: blocking call `{name}` inside async def "
              f"(annotate `# {ALLOW_MARK}: <reason>` if the stall is "
              f"genuinely bounded)")
    if findings:
        print(f"{len(findings)} blocking call(s) found")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
