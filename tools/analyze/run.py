#!/usr/bin/env python
"""CLI for the static-analysis framework.

    python tools/analyze/run.py                    # all passes, human
    python tools/analyze/run.py --json             # machine schema
    python tools/analyze/run.py --sarif out.sarif  # SARIF 2.1.0 file
    python tools/analyze/run.py --pass jit_hazards --pass flag_drift
    python tools/analyze/run.py yugabyte_db_tpu/sched   # narrower roots
    python tools/analyze/run.py --changed origin/main..HEAD   # CI mode

Exit status: 1 when any unsuppressed finding exists, else 0 (2 on a
bad --changed range).

Incremental modes (``--staged`` for the pre-commit hook, ``--changed
<git-range>`` for CI / pre-push) still analyze the WHOLE tree — the
interprocedural passes need every caller — but report only findings
in the staged/changed files.  Repeat runs stay cheap because the call
graph's per-file facts persist under ``.analyze_cache/`` keyed on
(path, mtime, size); ``--no-cache`` opts out.

The ``--json`` schema (consumed by tests/test_analysis.py and the
bench.py WARN tail):

    {"passes": [{"id", "title", "findings": N, "suppressed": N,
                 "wall_ms": F}],
     "findings": [{"path", "line", "pass", "message", "detail",
                   "hint"}],
     "suppressions": {pass_id: N},
     "total_findings": N, "total_suppressed": N, "wall_ms": F,
     "parse_errors": [{"path", "error"}]}

``--sarif <path>`` additionally writes the unsuppressed findings as a
single-run SARIF 2.1.0 log (rules = the executed passes, ruleId = the
pass id, the pass hint as the rule help text) so CI code-scanning
uploads can annotate the diff; it composes with every other mode and
does not change the exit status.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))       # tools/ -> `analyze`

from analyze import ALL_PASSES, ProjectIndex, get_pass, run_analysis  # noqa: E402
from analyze.core import DEFAULT_ROOTS  # noqa: E402


def _staged_files(base: str) -> list:
    """Repo-relative paths staged for commit (added/copied/modified/
    renamed — deletions have nothing to analyze)."""
    import subprocess
    try:
        r = subprocess.run(
            ["git", "diff", "--cached", "--name-only",
             "--diff-filter=ACMR"],
            cwd=base, capture_output=True, text=True, timeout=30,
            check=True)
    except (OSError, subprocess.SubprocessError):
        return []
    return [ln.strip() for ln in r.stdout.splitlines() if ln.strip()]


def _changed_files(base: str, git_range: str):
    """Repo-relative paths changed across ``git_range`` (committed
    AND working-tree edits — `run.py --changed origin/main` right
    before committing sees what the commit will contain).  Returns
    None when git cannot resolve the range."""
    import subprocess
    try:
        r = subprocess.run(
            ["git", "diff", "--name-only", "--diff-filter=ACMR",
             git_range, "--"],
            cwd=base, capture_output=True, text=True, timeout=30,
            check=True)
    except (OSError, subprocess.SubprocessError):
        return None
    return [ln.strip() for ln in r.stdout.splitlines() if ln.strip()]


def _index_content(base: str, rel: str):
    """The staged (index) content of `rel`, or None when unreadable."""
    import subprocess
    try:
        r = subprocess.run(["git", "show", f":{rel}"], cwd=base,
                           capture_output=True, timeout=30, check=True)
    except (OSError, subprocess.SubprocessError):
        return None
    return r.stdout.decode("utf-8", "replace")


def _sarif_log(report: dict, passes) -> dict:
    """The report as a one-run SARIF 2.1.0 log.  Pass ids become rule
    ids (hint text as the rule help); parse errors ship as tool
    notifications so an upload still shows WHY coverage shrank."""
    by_id = {p.id: p for p in passes}
    rules = [{
        "id": pid,
        "name": pid,
        "shortDescription": {"text": by_id[pid].title},
        "help": {"text": by_id[pid].hint},
        "defaultConfiguration": {"level": "error"},
    } for pid in sorted(by_id)]
    rule_index = {r["id"]: i for i, r in enumerate(rules)}
    results = [{
        "ruleId": f["pass"],
        "ruleIndex": rule_index[f["pass"]],
        "level": "error",
        "message": {"text": f["message"]},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f["path"],
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(1, f["line"])},
            },
        }],
    } for f in report["findings"]]
    notifications = [{
        "level": "error",
        "message": {"text": f"parse error: {e['error']}"},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": e["path"],
                                     "uriBaseId": "SRCROOT"},
            },
        }],
    } for e in report["parse_errors"]]
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "yugabyte-tpu-analyze",
                "informationUri": "tools/analyze/run.py",
                "rules": rules,
            }},
            "invocations": [{
                "executionSuccessful": True,
                "toolExecutionNotifications": notifications,
            }],
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }


def _write_sarif(path: str, log: dict) -> None:
    if path == "-":
        print(json.dumps(log))
        return
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(log, fh, indent=2)
        fh.write("\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="multi-pass static analysis for event-loop, "
                    "JAX-kernel and concurrency hazards")
    ap.add_argument("roots", nargs="*", default=list(DEFAULT_ROOTS),
                    help="analysis roots relative to the repo "
                         "(default: %s)" % (DEFAULT_ROOTS,))
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine schema on stdout")
    ap.add_argument("--sarif", metavar="PATH",
                    help="also write unsuppressed findings as a SARIF "
                         "2.1.0 log to PATH (ruleId = pass id; '-' "
                         "for stdout)")
    ap.add_argument("--pass", action="append", dest="passes", default=[],
                    metavar="ID", help="run only this pass (repeatable)")
    ap.add_argument("--base", default=os.path.dirname(os.path.dirname(_HERE)),
                    help="repo root (default: two levels up)")
    ap.add_argument("--staged", action="store_true",
                    help="analyze only git-staged .py files inside the "
                         "default analysis roots (the pre-commit hook "
                         "mode; exits 0 when nothing relevant is "
                         "staged)")
    ap.add_argument("--changed", metavar="GIT-RANGE",
                    help="report only findings in files changed across "
                         "this git range (e.g. origin/main..HEAD); the "
                         "index still covers the whole tree so "
                         "interprocedural findings stay sound")
    ap.add_argument("--no-cache", action="store_true",
                    help="skip the persisted .analyze_cache/ facts "
                         "cache (forces a full re-parse)")
    args = ap.parse_args(argv)

    passes = ([get_pass(p) for p in args.passes] if args.passes
              else list(ALL_PASSES))
    roots = args.roots
    focus = None        # report-only file set (staged/changed modes)
    focus_label = None
    if args.staged:
        focus = {f for f in _staged_files(args.base)
                 if f.endswith(".py")
                 and any(f == r or f.startswith(r.rstrip("/") + "/")
                         for r in DEFAULT_ROOTS)}
        focus_label = "staged"
    elif args.changed:
        changed = _changed_files(args.base, args.changed)
        if changed is None:
            print(f"analyze --changed: git could not resolve range "
                  f"{args.changed!r}", file=sys.stderr)
            return 2
        focus = {f for f in changed
                 if f.endswith(".py")
                 and any(f == r or f.startswith(r.rstrip("/") + "/")
                         for r in DEFAULT_ROOTS)}
        focus_label = f"changed in {args.changed}"
    if focus is not None:
        if not focus:
            if args.sarif:
                _write_sarif(args.sarif, _sarif_log(
                    {"findings": [], "parse_errors": []}, passes))
            if args.as_json:
                print(json.dumps({"passes": [], "findings": [],
                                  "suppressions": {}, "total_findings": 0,
                                  "total_suppressed": 0, "wall_ms": 0.0,
                                  "parse_errors": []}))
            else:
                print(f"analyze: no {focus_label} files under "
                      f"{DEFAULT_ROOTS}; nothing to check")
            return 0
        # whole-program passes (flag_drift's defs-vs-reads join, the
        # call graph) are only meaningful over the full roots: analyze
        # EVERYTHING, then gate on findings in the focus files alone
        roots = list(DEFAULT_ROOTS)
    # staged files are analyzed at their INDEX content, not the working
    # tree — a partially staged file is checked against the bytes that
    # will actually land in the commit.  --changed deliberately reads
    # the CHECKOUT: in CI the checkout IS the range head; a local
    # pre-push from a dirty tree is told about the hazards as they
    # stand now (the next push re-checks whatever actually lands)
    overlay = {rel: src for rel in (focus if args.staged else ())
               if (src := _index_content(args.base, rel)) is not None}
    cache_dir = None if args.no_cache else os.path.join(
        args.base, ".analyze_cache")
    index = ProjectIndex(args.base, roots=roots, overlay=overlay,
                         cache_dir=cache_dir)
    report = run_analysis(index, passes)
    if focus is not None:
        report["findings"] = [f for f in report["findings"]
                              if f["path"] in focus]
        report["parse_errors"] = [e for e in report["parse_errors"]
                                  if e["path"] in focus]
        report["total_findings"] = len(report["findings"])

    if args.sarif:
        _write_sarif(args.sarif, _sarif_log(report, passes))
    if args.as_json:
        print(json.dumps(report))
    else:
        for f in report["findings"]:
            h = f"  [fix: {f['hint']}]" if f["hint"] else ""
            print(f"{f['path']}:{f['line']}: [{f['pass']}] "
                  f"{f['message']}{h}")
        for e in report["parse_errors"]:
            print(f"{e['path']}: PARSE ERROR {e['error']}")
        tally = ", ".join(
            f"{p['id']}: {p['findings']} finding(s), {p['suppressed']} "
            f"suppressed, {p['wall_ms']:.0f}ms"
            for p in report["passes"])
        print(f"-- {tally}")
        print(f"-- total: {report['total_findings']} finding(s), "
              f"{report['total_suppressed']} suppressed, "
              f"{report['wall_ms']:.0f}ms")
    return 1 if (report["total_findings"]
                 or report["parse_errors"]) else 0


if __name__ == "__main__":
    sys.exit(main())
