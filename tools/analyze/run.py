#!/usr/bin/env python
"""CLI for the static-analysis framework.

    python tools/analyze/run.py                    # all passes, human
    python tools/analyze/run.py --json             # machine schema
    python tools/analyze/run.py --pass jit_hazards --pass flag_drift
    python tools/analyze/run.py yugabyte_db_tpu/sched   # narrower roots

Exit status: 1 when any unsuppressed finding exists, else 0.

The ``--json`` schema (consumed by tests/test_analysis.py and the
bench.py WARN tail):

    {"passes": [{"id", "title", "findings": N, "suppressed": N,
                 "wall_ms": F}],
     "findings": [{"path", "line", "pass", "message", "detail",
                   "hint"}],
     "suppressions": {pass_id: N},
     "total_findings": N, "total_suppressed": N, "wall_ms": F,
     "parse_errors": [{"path", "error"}]}
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))       # tools/ -> `analyze`

from analyze import ALL_PASSES, ProjectIndex, get_pass, run_analysis  # noqa: E402
from analyze.core import DEFAULT_ROOTS  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="multi-pass static analysis for event-loop, "
                    "JAX-kernel and concurrency hazards")
    ap.add_argument("roots", nargs="*", default=list(DEFAULT_ROOTS),
                    help="analysis roots relative to the repo "
                         "(default: %s)" % (DEFAULT_ROOTS,))
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine schema on stdout")
    ap.add_argument("--pass", action="append", dest="passes", default=[],
                    metavar="ID", help="run only this pass (repeatable)")
    ap.add_argument("--base", default=os.path.dirname(os.path.dirname(_HERE)),
                    help="repo root (default: two levels up)")
    args = ap.parse_args(argv)

    passes = ([get_pass(p) for p in args.passes] if args.passes
              else list(ALL_PASSES))
    index = ProjectIndex(args.base, roots=args.roots)
    report = run_analysis(index, passes)

    if args.as_json:
        print(json.dumps(report))
    else:
        for f in report["findings"]:
            h = f"  [fix: {f['hint']}]" if f["hint"] else ""
            print(f"{f['path']}:{f['line']}: [{f['pass']}] "
                  f"{f['message']}{h}")
        for e in report["parse_errors"]:
            print(f"{e['path']}: PARSE ERROR {e['error']}")
        tally = ", ".join(
            f"{p['id']}: {p['findings']} finding(s), {p['suppressed']} "
            f"suppressed, {p['wall_ms']:.0f}ms"
            for p in report["passes"])
        print(f"-- {tally}")
        print(f"-- total: {report['total_findings']} finding(s), "
              f"{report['total_suppressed']} suppressed, "
              f"{report['wall_ms']:.0f}ms")
    return 1 if (report["total_findings"]
                 or report["parse_errors"]) else 0


if __name__ == "__main__":
    sys.exit(main())
