#!/usr/bin/env python
"""CLI for the static-analysis framework.

    python tools/analyze/run.py                    # all passes, human
    python tools/analyze/run.py --json             # machine schema
    python tools/analyze/run.py --pass jit_hazards --pass flag_drift
    python tools/analyze/run.py yugabyte_db_tpu/sched   # narrower roots

Exit status: 1 when any unsuppressed finding exists, else 0.

The ``--json`` schema (consumed by tests/test_analysis.py and the
bench.py WARN tail):

    {"passes": [{"id", "title", "findings": N, "suppressed": N,
                 "wall_ms": F}],
     "findings": [{"path", "line", "pass", "message", "detail",
                   "hint"}],
     "suppressions": {pass_id: N},
     "total_findings": N, "total_suppressed": N, "wall_ms": F,
     "parse_errors": [{"path", "error"}]}
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))       # tools/ -> `analyze`

from analyze import ALL_PASSES, ProjectIndex, get_pass, run_analysis  # noqa: E402
from analyze.core import DEFAULT_ROOTS  # noqa: E402


def _staged_files(base: str) -> list:
    """Repo-relative paths staged for commit (added/copied/modified/
    renamed — deletions have nothing to analyze)."""
    import subprocess
    try:
        r = subprocess.run(
            ["git", "diff", "--cached", "--name-only",
             "--diff-filter=ACMR"],
            cwd=base, capture_output=True, text=True, timeout=30,
            check=True)
    except (OSError, subprocess.SubprocessError):
        return []
    return [ln.strip() for ln in r.stdout.splitlines() if ln.strip()]


def _index_content(base: str, rel: str):
    """The staged (index) content of `rel`, or None when unreadable."""
    import subprocess
    try:
        r = subprocess.run(["git", "show", f":{rel}"], cwd=base,
                           capture_output=True, timeout=30, check=True)
    except (OSError, subprocess.SubprocessError):
        return None
    return r.stdout.decode("utf-8", "replace")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="multi-pass static analysis for event-loop, "
                    "JAX-kernel and concurrency hazards")
    ap.add_argument("roots", nargs="*", default=list(DEFAULT_ROOTS),
                    help="analysis roots relative to the repo "
                         "(default: %s)" % (DEFAULT_ROOTS,))
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine schema on stdout")
    ap.add_argument("--pass", action="append", dest="passes", default=[],
                    metavar="ID", help="run only this pass (repeatable)")
    ap.add_argument("--base", default=os.path.dirname(os.path.dirname(_HERE)),
                    help="repo root (default: two levels up)")
    ap.add_argument("--staged", action="store_true",
                    help="analyze only git-staged .py files inside the "
                         "default analysis roots (the pre-commit hook "
                         "mode; exits 0 when nothing relevant is "
                         "staged)")
    args = ap.parse_args(argv)

    passes = ([get_pass(p) for p in args.passes] if args.passes
              else list(ALL_PASSES))
    roots = args.roots
    staged = None
    if args.staged:
        staged = {f for f in _staged_files(args.base)
                  if f.endswith(".py")
                  and any(f == r or f.startswith(r.rstrip("/") + "/")
                          for r in DEFAULT_ROOTS)}
        if not staged:
            if args.as_json:
                print(json.dumps({"passes": [], "findings": [],
                                  "suppressions": {}, "total_findings": 0,
                                  "total_suppressed": 0, "wall_ms": 0.0,
                                  "parse_errors": []}))
            else:
                print("analyze --staged: no staged files under "
                      f"{DEFAULT_ROOTS}; nothing to check")
            return 0
        # whole-program passes (flag_drift's defs-vs-reads join) are
        # only meaningful over the full roots: analyze EVERYTHING, then
        # gate the commit on findings in the staged files alone
        roots = list(DEFAULT_ROOTS)
    # staged files are analyzed at their INDEX content, not the working
    # tree — a partially staged file is checked against the bytes that
    # will actually land in the commit
    overlay = {rel: src for rel in (staged or ())
               if (src := _index_content(args.base, rel)) is not None}
    index = ProjectIndex(args.base, roots=roots, overlay=overlay)
    report = run_analysis(index, passes)
    if staged is not None:
        report["findings"] = [f for f in report["findings"]
                              if f["path"] in staged]
        report["parse_errors"] = [e for e in report["parse_errors"]
                                  if e["path"] in staged]
        report["total_findings"] = len(report["findings"])

    if args.as_json:
        print(json.dumps(report))
    else:
        for f in report["findings"]:
            h = f"  [fix: {f['hint']}]" if f["hint"] else ""
            print(f"{f['path']}:{f['line']}: [{f['pass']}] "
                  f"{f['message']}{h}")
        for e in report["parse_errors"]:
            print(f"{e['path']}: PARSE ERROR {e['error']}")
        tally = ", ".join(
            f"{p['id']}: {p['findings']} finding(s), {p['suppressed']} "
            f"suppressed, {p['wall_ms']:.0f}ms"
            for p in report["passes"])
        print(f"-- {tally}")
        print(f"-- total: {report['total_findings']} finding(s), "
              f"{report['total_suppressed']} suppressed, "
              f"{report['wall_ms']:.0f}ms")
    return 1 if (report["total_findings"]
                 or report["parse_errors"]) else 0


if __name__ == "__main__":
    sys.exit(main())
