#!/usr/bin/env python
"""Install the static-analysis sweep as git pre-commit/pre-push hooks.

    python tools/analyze/install_hook.py             # install pre-commit
    python tools/analyze/install_hook.py --pre-push  # + pre-push (CI twin)
    python tools/analyze/install_hook.py --uninstall # remove ours
    python tools/analyze/install_hook.py --force     # replace foreign hook

The pre-commit hook runs ``tools/analyze/run.py --staged`` — the full
pass set over the whole tree, findings gated to the STAGED .py files —
so findings land at commit time instead of in the next tier-1 run.
The optional pre-push hook runs ``run.py --changed <remote>..<local>``
per pushed ref: the same incremental report CI runs, catching commits
made with ``--no-verify`` before they leave the machine.  A hook
failure blocks the commit/push; annotate with
``# analysis-ok(<pass>): <reason>`` (see ANALYSIS.md) or fix the
hazard.  ``git commit/push --no-verify`` bypasses in an emergency.
Repeat runs reuse the persisted ``.analyze_cache/`` facts, so the
hook's cost is one tree walk plus the changed files' re-extraction.

The installer refuses to overwrite a pre-existing hook it did not
write (``--force`` replaces it), and uninstall removes only our own.
"""
from __future__ import annotations

import argparse
import os
import stat
import subprocess
import sys

MARKER = "# installed by tools/analyze/install_hook.py"

HOOK = f"""#!/bin/sh
{MARKER}
# Static-analysis sweep over staged files; blocks the commit on any
# unsuppressed finding. Bypass in an emergency: git commit --no-verify
repo_root=$(git rev-parse --show-toplevel) || exit 0
exec "${{ANALYZE_PYTHON:-python3}}" \\
    "$repo_root/tools/analyze/run.py" --staged --base "$repo_root"
"""

PUSH_HOOK = f"""#!/bin/sh
{MARKER}
# Static-analysis sweep over the commits being pushed (the CI report,
# run locally). Bypass in an emergency: git push --no-verify
repo_root=$(git rev-parse --show-toplevel) || exit 0
status=0
while read local_ref local_sha remote_ref remote_sha; do
    # branch deletion: nothing outgoing to analyze
    case "$local_sha" in *[!0]*) ;; *) continue ;; esac
    if case "$remote_sha" in *[!0]*) false ;; esac; then
        # new remote branch: no base to diff against — full sweep
        range=""
    else
        range="$remote_sha..$local_sha"
    fi
    if [ -n "$range" ]; then
        "${{ANALYZE_PYTHON:-python3}}" \\
            "$repo_root/tools/analyze/run.py" \\
            --changed "$range" --base "$repo_root" || status=1
    else
        "${{ANALYZE_PYTHON:-python3}}" \\
            "$repo_root/tools/analyze/run.py" \\
            --base "$repo_root" || status=1
    fi
done
exit $status
"""


def _git_dir(base: str) -> str:
    r = subprocess.run(["git", "rev-parse", "--git-dir"], cwd=base,
                       capture_output=True, text=True, check=True)
    path = r.stdout.strip()
    return path if os.path.isabs(path) else os.path.join(base, path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="install/remove the analysis pre-commit hook")
    ap.add_argument("--base", default=os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
        help="repo root (default: two levels up from this file)")
    ap.add_argument("--force", action="store_true",
                    help="replace a pre-existing foreign hook")
    ap.add_argument("--uninstall", action="store_true",
                    help="remove the hook(s) if (and only if) we "
                         "installed them")
    ap.add_argument("--pre-push", action="store_true", dest="pre_push",
                    help="also install the pre-push hook (run.py "
                         "--changed over each pushed ref — the CI "
                         "report, locally)")
    args = ap.parse_args(argv)

    try:
        hooks_dir = os.path.join(_git_dir(args.base), "hooks")
    except (OSError, subprocess.SubprocessError) as e:
        print(f"not a git repository ({e}); nothing to install",
              file=sys.stderr)
        return 1
    os.makedirs(hooks_dir, exist_ok=True)

    hooks = [("pre-commit", HOOK,
              "runs `tools/analyze/run.py --staged` on every commit")]
    if args.pre_push or args.uninstall:
        hooks.append(("pre-push", PUSH_HOOK,
                      "runs `tools/analyze/run.py --changed "
                      "<remote>..<local>` on every push"))

    rc = 0
    for name, content, blurb in hooks:
        hook_path = os.path.join(hooks_dir, name)
        existing = None
        if os.path.exists(hook_path):
            with open(hook_path, encoding="utf-8", errors="replace") as f:
                existing = f.read()

        if args.uninstall:
            if existing is None:
                print(f"no {name} hook installed")
                continue
            if MARKER not in existing:
                print(f"{hook_path} was not installed by this tool; "
                      f"refusing to remove it", file=sys.stderr)
                rc = 1
                continue
            os.unlink(hook_path)
            print(f"removed {hook_path}")
            continue

        if existing is not None and MARKER not in existing \
                and not args.force:
            print(f"{hook_path} already exists and was not installed "
                  f"by this tool; re-run with --force to replace it",
                  file=sys.stderr)
            rc = 1
            continue
        with open(hook_path, "w") as f:
            f.write(content)
        os.chmod(hook_path, os.stat(hook_path).st_mode | stat.S_IXUSR
                 | stat.S_IXGRP | stat.S_IXOTH)
        print(f"installed {hook_path} ({blurb}; bypass with "
              f"--no-verify)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
