#!/usr/bin/env python
"""Install the static-analysis sweep as a git pre-commit hook.

    python tools/analyze/install_hook.py             # install
    python tools/analyze/install_hook.py --uninstall # remove ours
    python tools/analyze/install_hook.py --force     # replace foreign hook

The hook runs ``tools/analyze/run.py --staged`` — the full pass set
over only the STAGED .py files inside the analysis roots — so findings
land at commit time instead of in the next tier-1 run.  A commit with
unsuppressed findings is blocked; annotate with
``# analysis-ok(<pass>): <reason>`` (see ANALYSIS.md) or fix the
hazard.  ``git commit --no-verify`` bypasses in an emergency.

The installer refuses to overwrite a pre-existing hook it did not
write (``--force`` replaces it), and uninstall removes only our own.
"""
from __future__ import annotations

import argparse
import os
import stat
import subprocess
import sys

MARKER = "# installed by tools/analyze/install_hook.py"

HOOK = f"""#!/bin/sh
{MARKER}
# Static-analysis sweep over staged files; blocks the commit on any
# unsuppressed finding. Bypass in an emergency: git commit --no-verify
repo_root=$(git rev-parse --show-toplevel) || exit 0
exec "${{ANALYZE_PYTHON:-python3}}" \\
    "$repo_root/tools/analyze/run.py" --staged --base "$repo_root"
"""


def _git_dir(base: str) -> str:
    r = subprocess.run(["git", "rev-parse", "--git-dir"], cwd=base,
                       capture_output=True, text=True, check=True)
    path = r.stdout.strip()
    return path if os.path.isabs(path) else os.path.join(base, path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="install/remove the analysis pre-commit hook")
    ap.add_argument("--base", default=os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
        help="repo root (default: two levels up from this file)")
    ap.add_argument("--force", action="store_true",
                    help="replace a pre-existing foreign pre-commit hook")
    ap.add_argument("--uninstall", action="store_true",
                    help="remove the hook if (and only if) we installed it")
    args = ap.parse_args(argv)

    try:
        hooks_dir = os.path.join(_git_dir(args.base), "hooks")
    except (OSError, subprocess.SubprocessError) as e:
        print(f"not a git repository ({e}); nothing to install",
              file=sys.stderr)
        return 1
    os.makedirs(hooks_dir, exist_ok=True)
    hook_path = os.path.join(hooks_dir, "pre-commit")
    existing = None
    if os.path.exists(hook_path):
        with open(hook_path, encoding="utf-8", errors="replace") as f:
            existing = f.read()

    if args.uninstall:
        if existing is None:
            print("no pre-commit hook installed")
            return 0
        if MARKER not in existing:
            print(f"{hook_path} was not installed by this tool; "
                  f"refusing to remove it", file=sys.stderr)
            return 1
        os.unlink(hook_path)
        print(f"removed {hook_path}")
        return 0

    if existing is not None and MARKER not in existing and not args.force:
        print(f"{hook_path} already exists and was not installed by "
              f"this tool; re-run with --force to replace it",
              file=sys.stderr)
        return 1
    with open(hook_path, "w") as f:
        f.write(HOOK)
    os.chmod(hook_path, os.stat(hook_path).st_mode | stat.S_IXUSR
             | stat.S_IXGRP | stat.S_IXOTH)
    print(f"installed {hook_path} (runs `tools/analyze/run.py --staged` "
          f"on every commit; bypass with --no-verify)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
