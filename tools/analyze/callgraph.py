"""Project-wide call graph: the interprocedural layer under the passes.

The lexical passes see one function at a time; this module sees the
whole program.  Three stages, all name-based (the analyzer never
imports the code it checks):

1. **Facts extraction** — one AST walk per file produces a
   JSON-serializable facts dict: every def (module functions, class
   methods, nested defs) with its raw call sites, every class with its
   bases / methods / ``self.X`` attribute assignments, the import
   table (absolute and relative spellings, ``as`` renames), and
   module-level aliases (``fn = mod.helper``).  Facts are *per-file
   pure*, which is what makes the on-disk cache sound: an entry keyed
   on ``(path, mtime_ns, size)`` can never go stale because of an edit
   to a *different* file.

2. **Resolution** — a call's dotted text is resolved in its def's
   scope: local nested defs, module defs/classes, alias chains
   (bounded), the import table, ``self.x()``/``cls.x()`` through the
   enclosing class's MRO (project-local bases followed cross-module),
   ``ClassName.x()``, absolute ``pkg.mod.fn`` forms, and — one hop of
   attribute typing — ``self.attr.m()`` where the enclosing class
   assigns ``self.attr = Ctor(...)`` or ``self.attr = param`` with an
   annotated parameter (the ``TabletPeer.tablet -> Tablet`` shape the
   write-path hot-path rule needs; conflicting assignments kill the
   type).  Unresolvable targets return None — propagation
   under-approximates rather than guesses (a terminal-name fallback is
   each pass's own choice).

3. **Summaries** — ``summarize()`` computes per-def hazard summaries
   (e.g. "blocking calls reachable from here") as a memoized DFS over
   the edge lists, cycle-guarded and depth-bounded, storing one
   *witness step* per hazard so full call chains can be reconstructed
   for findings without storing exponential path sets.

Known resolution limits (ANALYSIS.md "interprocedural contract"):
calls through container/attribute indirection (``self.handlers[k]()``,
``obj.attr.fn()`` where ``obj`` is not self/cls/a module), calls on
values returned from calls, lambda bodies, and ``functools.partial``
objects invoked later are not resolved; star imports are ignored;
alias chains are followed to depth 6 and summaries to depth 25.
"""
from __future__ import annotations

import ast
import json
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

from .core import ModuleInfo, call_name

#: bump to invalidate every persisted .analyze_cache facts entry when
#: the extraction schema changes
FACTS_VERSION = 2

#: alias chains (`a = b`, `b = mod.f`) followed at most this deep
_ALIAS_DEPTH = 6
#: summaries stop descending past this call depth (recursion guard is
#: separate; this bounds pathological but acyclic chains)
_SUMMARY_DEPTH = 25


def module_dotted(rel: str) -> str:
    """Repo-relative file path -> dotted module name
    (``pkg/sub/__init__.py`` -> ``pkg.sub``)."""
    rel = rel.replace("\\", "/")
    if rel.endswith("/__init__.py"):
        rel = rel[: -len("/__init__.py")]
    elif rel.endswith("__init__.py"):
        rel = rel[: -len("__init__.py")].rstrip("/")
    elif rel.endswith(".py"):
        rel = rel[:-3]
    return rel.replace("/", ".")


def _package_parts(rel: str) -> List[str]:
    """The package a module's relative imports resolve against."""
    dotted = module_dotted(rel)
    parts = dotted.split(".") if dotted else []
    if rel.replace("\\", "/").endswith("/__init__.py"):
        return parts               # the package itself
    return parts[:-1]


def iter_defs(tree: ast.Module):
    """Yield ``(qual, cls_qual, node)`` for every function def in the
    module, with the SAME qual scheme the facts extractor uses — the
    bridge that lets a pass walking the AST look its current def up in
    the graph.  ``cls_qual`` is the enclosing class qual when the def
    is a direct class member, else None."""

    def walk(stmts, scope: List[str], cls: Optional[str]):
        for s in stmts:
            if isinstance(s, ast.ClassDef):
                cqual = ".".join(scope + [s.name])
                yield from walk(s.body, scope + [s.name], cqual)
            elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(scope + [s.name])
                yield qual, cls, s
                yield from walk(s.body, scope + [s.name], None)
            else:
                children = [c for c in ast.iter_child_nodes(s)
                            if isinstance(c, (ast.stmt,
                                              ast.ExceptHandler,
                                              ast.match_case))]
                if children:
                    yield from walk(children, scope, cls)

    yield from walk(tree.body, [], None)


def _collect_calls(body) -> List[List]:
    """Raw ``[line, dotted-text]`` call sites in a def body, stopping
    at nested def/class/lambda boundaries (those run in their own
    context — a nested def only contributes when it is CALLED, which
    shows up as an edge to its own def)."""
    out: List[List] = []

    def go(n: ast.AST) -> None:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            return
        if isinstance(n, ast.Call):
            t = call_name(n)
            if t:
                out.append([n.lineno, t])
        for c in ast.iter_child_nodes(n):
            go(c)

    for s in body:
        go(s)
    return out


def _note_attr_type(centry: dict, attr: str, assign: ast.AST,
                    ann: Dict[str, str]) -> None:
    """Record ``self.<attr>``'s class when the assignment shape names
    one: ``self.x = Ctor(...)`` (the constructor's dotted text) or
    ``self.x = param`` with an annotated parameter.  Assignments that
    disagree — or any re-assignment the shapes can't type, past the
    initial ``self.x = None`` idiom — poison the attr (recorded as
    None) so resolution under-approximates instead of guessing."""
    types = centry.setdefault("attr_types", {})
    v = assign.value if not isinstance(assign, ast.AugAssign) else None
    t: Optional[str] = None
    if isinstance(v, ast.Call):
        t = call_name(v)
        # lowercase head = a factory function, not a class ctor; typing
        # through it would need return-type inference — skip
        if not t or not t.split(".")[-1][:1].isupper():
            t = None
    elif isinstance(v, ast.Name):
        t = ann.get(v.id)
    elif isinstance(v, ast.Constant) and v.value is None:
        return          # `self.x = None` (Optional idiom): neutral —
        #                 the non-None assignment governs the type
    if attr in types and types[attr] != t:
        types[attr] = None              # conflicting shapes: poison
    elif t is not None:
        types.setdefault(attr, t)
    else:
        types[attr] = None              # untypeable re-assignment


def extract_facts(mod: ModuleInfo) -> dict:
    """One module's call-graph facts (pure function of the file)."""
    pkg = _package_parts(mod.rel)
    facts: dict = {"module": module_dotted(mod.rel), "defs": {},
                   "classes": {}, "imports": {}, "aliases": {},
                   "globals": []}
    if mod.tree is None:
        return facts
    gl: set = set()

    def add_import(node) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    facts["imports"][a.asname] = a.name
            return
        if node.level:
            base = pkg[: len(pkg) - (node.level - 1)] if node.level > 1 \
                else list(pkg)
            target = ".".join(base + ([node.module] if node.module
                                      else []))
        else:
            target = node.module or ""
        for a in node.names:
            if a.name == "*":
                continue
            full = f"{target}.{a.name}" if target else a.name
            facts["imports"][a.asname or a.name] = full

    def add_def(node, scope: List[str], cls: Optional[str]) -> None:
        qual = ".".join(scope + [node.name])
        locals_: Dict[str, str] = {}
        for s in node.body:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                locals_[s.name] = f"{qual}.{s.name}"
        facts["defs"][qual] = {
            "name": node.name, "qual": qual,
            "async": isinstance(node, ast.AsyncFunctionDef),
            "line": node.lineno, "cls": cls,
            "calls": _collect_calls(node.body),
            "locals": locals_,
        }
        if cls is not None:
            centry = facts["classes"].get(cls)
            if centry is not None:
                centry["methods"][node.name] = qual
                # parameter annotations type `self.x = param` assigns
                ann: Dict[str, str] = {}
                for a in (node.args.posonlyargs + node.args.args
                          + node.args.kwonlyargs):
                    if a.annotation is None:
                        continue
                    t = a.annotation
                    if isinstance(t, ast.Constant) and \
                            isinstance(t.value, str):
                        ann[a.arg] = t.value        # "Tablet" string form
                    elif isinstance(t, (ast.Name, ast.Attribute)):
                        ann[a.arg] = ast.unparse(t)
                for n in ast.walk(node):
                    if isinstance(n, (ast.Assign, ast.AnnAssign,
                                      ast.AugAssign)):
                        tgts = n.targets if isinstance(n, ast.Assign) \
                            else [n.target]
                        for t in tgts:
                            if isinstance(t, ast.Attribute) \
                                    and isinstance(t.value, ast.Name) \
                                    and t.value.id == "self":
                                if t.attr not in centry["attrs"]:
                                    centry["attrs"].append(t.attr)
                                _note_attr_type(centry, t.attr, n, ann)

    def walk(stmts, scope: List[str], cls: Optional[str],
             top: bool) -> None:
        for s in stmts:
            if isinstance(s, (ast.Import, ast.ImportFrom)):
                add_import(s)
            elif isinstance(s, ast.ClassDef):
                cqual = ".".join(scope + [s.name])
                facts["classes"][cqual] = {
                    "bases": [ast.unparse(b) for b in s.bases
                              if isinstance(b, (ast.Name,
                                                ast.Attribute))],
                    "methods": {}, "attrs": []}
                if top:
                    gl.add(s.name)
                walk(s.body, scope + [s.name], cqual, False)
            elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add_def(s, scope, cls)
                if top:
                    gl.add(s.name)
                walk(s.body, scope + [s.name], None, False)
            else:
                if top and isinstance(s, (ast.Assign, ast.AnnAssign)):
                    tgts = s.targets if isinstance(s, ast.Assign) \
                        else [s.target]
                    for t in tgts:
                        if isinstance(t, ast.Name):
                            gl.add(t.id)
                            if isinstance(s.value, (ast.Name,
                                                    ast.Attribute)):
                                facts["aliases"][t.id] = \
                                    ast.unparse(s.value)
                children = [c for c in ast.iter_child_nodes(s)
                            if isinstance(c, (ast.stmt,
                                              ast.ExceptHandler,
                                              ast.match_case))]
                if children:
                    walk(children, scope, cls, top)

    walk(mod.tree.body, [], None, True)
    facts["globals"] = sorted(gl)
    return facts


# --- persisted facts cache -------------------------------------------------

class FactsCache:
    """One JSON file under ``.analyze_cache/`` mapping rel path ->
    ``{"k": [mtime_ns, size], "f": facts}``.  A stale key or a
    FACTS_VERSION bump is simply a miss; writes go through a tmp +
    atomic-replace so a crashed run never leaves a torn cache."""

    def __init__(self, cache_dir: str):
        self.path = os.path.join(cache_dir, "callgraph_facts.json")
        self._dirty = False
        self._files: Dict[str, dict] = {}
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
            if data.get("v") == FACTS_VERSION:
                self._files = data.get("files", {})
        except (OSError, ValueError):
            pass

    def get(self, rel: str, key: Optional[Tuple[int, int]]):
        if key is None:
            return None
        e = self._files.get(rel)
        if e is not None and e.get("k") == list(key):
            return e["f"]
        return None

    def put(self, rel: str, key: Optional[Tuple[int, int]],
            facts: dict) -> None:
        if key is None:
            return
        self._files[rel] = {"k": list(key), "f": facts}
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"v": FACTS_VERSION, "files": self._files}, f)
            os.replace(tmp, self.path)
            self._dirty = False
        except OSError:
            pass                      # cache is best-effort only


# --- the graph -------------------------------------------------------------

class CallGraph:
    """Resolution + edges + summaries over the extracted facts.

    Def keys are ``"<rel>::<qual>"`` strings; ``None`` always means
    "could not resolve" and every consumer treats it as no-edge."""

    def __init__(self, facts_by_rel: Dict[str, dict], stats: dict):
        self.facts = facts_by_rel
        self.stats = stats
        self.mod_rel = {f["module"]: rel
                        for rel, f in facts_by_rel.items() if f["module"]}
        self._edges: Dict[str, List[Tuple[int, str, Optional[str]]]] = {}
        self._memos: Dict[str, Dict[str, dict]] = {}
        self.stats["defs"] = sum(len(f["defs"])
                                 for f in facts_by_rel.values())

    # -- lookups -----------------------------------------------------------
    @staticmethod
    def key(rel: str, qual: str) -> str:
        return f"{rel}::{qual}"

    @staticmethod
    def split(key: str) -> Tuple[str, str]:
        rel, _, qual = key.partition("::")
        return rel, qual

    def def_fact(self, key: str) -> Optional[dict]:
        rel, qual = self.split(key)
        f = self.facts.get(rel)
        return f["defs"].get(qual) if f else None

    def is_async(self, key: str) -> bool:
        d = self.def_fact(key)
        return bool(d and d["async"])

    def defs(self):
        for rel, f in self.facts.items():
            for qual, d in f["defs"].items():
                yield self.key(rel, qual), d

    def class_fact(self, rel: str, cls_qual: str) -> Optional[dict]:
        f = self.facts.get(rel)
        return f["classes"].get(cls_qual) if f else None

    # -- resolution --------------------------------------------------------
    def resolve(self, rel: str, def_qual: Optional[str],
                text: str) -> Optional[str]:
        """Resolve a call's dotted text in the scope of def
        ``def_qual`` of module ``rel`` (def_qual None = module scope).
        Returns a def key or None."""
        return self._resolve_text(rel, def_qual, text, 0)

    def _resolve_text(self, rel: str, def_qual: Optional[str],
                      text: str, depth: int) -> Optional[str]:
        if depth > _ALIAS_DEPTH or not text:
            return None
        f = self.facts.get(rel)
        if f is None:
            return None
        parts = text.split(".")
        head = parts[0]
        if head in ("self", "cls"):
            if def_qual is None or len(parts) not in (2, 3):
                return None
            d = f["defs"].get(def_qual)
            cls = d["cls"] if d else self._enclosing_class(rel, def_qual)
            if cls is None:
                return None
            if len(parts) == 2:
                return self.resolve_method(rel, cls, parts[1])
            # self.<attr>.<m>(): one hop through the attr's recorded
            # type (ctor / annotated-param assignment in this class's
            # MRO) — the TabletPeer.tablet.apply_write shape
            hit = self._attr_type(rel, cls, parts[1])
            if hit is None:
                return None
            return self.resolve_method(hit[0], hit[1], parts[2])
        if len(parts) == 1:
            # innermost-out: nested defs of the enclosing def chain
            if def_qual is not None:
                for anc in self._def_ancestry(f, def_qual):
                    loc = f["defs"][anc]["locals"].get(head)
                    if loc is not None:
                        return self.key(rel, loc)
            d = f["defs"].get(head)
            if d is not None and d["cls"] is None:
                return self.key(rel, head)
            if head in f["classes"]:
                return self.resolve_method(rel, head, "__init__")
            if head in f["aliases"]:
                return self._resolve_text(rel, None, f["aliases"][head],
                                          depth + 1)
            if head in f["imports"]:
                return self._absolute(f["imports"][head])
            return None
        rest = ".".join(parts[1:])
        if head in f["aliases"]:
            return self._resolve_text(
                rel, None, f"{f['aliases'][head]}.{rest}", depth + 1)
        if head in f["imports"]:
            return self._absolute(f"{f['imports'][head]}.{rest}")
        if head in f["classes"] and len(parts) == 2:
            return self.resolve_method(rel, head, parts[1])
        return self._absolute(text)

    def _attr_type(self, rel: str, cls_qual: str, attr: str,
                   _seen=None) -> Optional[Tuple[str, str]]:
        """Resolve ``self.<attr>``'s class for (rel, cls_qual): walk
        the MRO for an ``attr_types`` entry and resolve the recorded
        type text in its DEFINING module's import context.  Returns
        ``(rel, cls_qual)`` of the attr's class, or None (unrecorded /
        poisoned / unresolvable)."""
        if _seen is None:
            _seen = set()
        if (rel, cls_qual) in _seen or len(_seen) > 32:
            return None
        _seen.add((rel, cls_qual))
        c = self.class_fact(rel, cls_qual)
        if c is None:
            return None
        t = c.get("attr_types", {}).get(attr)
        if t is not None:
            return self.resolve_class(rel, t)
        if attr in c.get("attr_types", {}):
            return None                 # poisoned: conflicting shapes
        for base in c["bases"]:
            hit = self.resolve_class(rel, base)
            if hit is None:
                continue
            r = self._attr_type(hit[0], hit[1], attr, _seen)
            if r is not None:
                return r
        return None

    def _def_ancestry(self, f: dict, def_qual: str) -> List[str]:
        """def_qual plus every enclosing def qual that exists, in
        innermost-out order (``f.g.h`` -> [f.g.h, f.g, f])."""
        out = []
        parts = def_qual.split(".")
        for i in range(len(parts), 0, -1):
            q = ".".join(parts[:i])
            if q in f["defs"]:
                out.append(q)
        return out

    def _enclosing_class(self, rel: str, def_qual: str) -> Optional[str]:
        f = self.facts[rel]
        parts = def_qual.split(".")
        for i in range(len(parts) - 1, 0, -1):
            q = ".".join(parts[:i])
            if q in f["classes"]:
                return q
        return None

    def _absolute(self, dotted: str) -> Optional[str]:
        """Resolve an absolute dotted target: longest module-path
        prefix owned by the project, remainder a def, a class
        (-> ``__init__``) or ``Class.method``."""
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            mod = ".".join(parts[:i])
            rel = self.mod_rel.get(mod)
            if rel is None:
                continue
            rest = parts[i:]
            f = self.facts[rel]
            if not rest:
                return None
            if len(rest) == 1:
                d = f["defs"].get(rest[0])
                if d is not None and d["cls"] is None:
                    return self.key(rel, rest[0])
                if rest[0] in f["classes"]:
                    return self.resolve_method(rel, rest[0], "__init__")
                alias = f["aliases"].get(rest[0])
                if alias is not None:
                    return self._resolve_text(rel, None, alias, 1)
                return None
            if len(rest) == 2 and rest[0] in f["classes"]:
                return self.resolve_method(rel, rest[0], rest[1])
            return None
        return None

    def resolve_class(self, rel: str, text: str,
                      _depth: int = 0) -> Optional[Tuple[str, str]]:
        """Resolve a class reference (base-class expr, ClassName use)
        to ``(rel, cls_qual)``."""
        if _depth > _ALIAS_DEPTH or not text:
            return None
        f = self.facts.get(rel)
        if f is None:
            return None
        parts = text.split(".")
        if len(parts) == 1:
            if text in f["classes"]:
                return rel, text
            if text in f["aliases"]:
                return self.resolve_class(rel, f["aliases"][text],
                                          _depth + 1)
            if text in f["imports"]:
                return self._absolute_class(f["imports"][text])
            return None
        head = parts[0]
        if head in f["aliases"]:
            return self.resolve_class(
                rel, ".".join([f["aliases"][head]] + parts[1:]),
                _depth + 1)
        if head in f["imports"]:
            return self._absolute_class(
                ".".join([f["imports"][head]] + parts[1:]))
        return self._absolute_class(text)

    def _absolute_class(self, dotted: str) -> Optional[Tuple[str, str]]:
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            mod = ".".join(parts[:i])
            rel = self.mod_rel.get(mod)
            if rel is None:
                continue
            rest = ".".join(parts[i:])
            if rest and rest in self.facts[rel]["classes"]:
                return rel, rest
            return None
        return None

    def resolve_method(self, rel: str, cls_qual: str, name: str,
                       _seen=None) -> Optional[str]:
        """Method lookup through the project-local MRO (DFS over bases,
        cross-module, visited-guarded)."""
        if _seen is None:
            _seen = set()
        if (rel, cls_qual) in _seen or len(_seen) > 32:
            return None
        _seen.add((rel, cls_qual))
        c = self.class_fact(rel, cls_qual)
        if c is None:
            return None
        q = c["methods"].get(name)
        if q is not None:
            return self.key(rel, q)
        for base in c["bases"]:
            hit = self.resolve_class(rel, base)
            if hit is None:
                continue
            r = self.resolve_method(hit[0], hit[1], name, _seen)
            if r is not None:
                return r
        return None

    def is_subclass(self, rel: str, cls_qual: str, anc_rel: str,
                    anc_qual: str, _seen=None) -> bool:
        """True when (rel, cls_qual) is (anc_rel, anc_qual) or inherits
        from it through project-local bases (cross-module, guarded)."""
        if (rel, cls_qual) == (anc_rel, anc_qual):
            return True
        if _seen is None:
            _seen = set()
        if (rel, cls_qual) in _seen or len(_seen) > 64:
            return False
        _seen.add((rel, cls_qual))
        c = self.class_fact(rel, cls_qual)
        if c is None:
            return False
        for base in c["bases"]:
            hit = self.resolve_class(rel, base)
            if hit is not None and self.is_subclass(
                    hit[0], hit[1], anc_rel, anc_qual, _seen):
                return True
        return False

    def defining_class(self, rel: str, cls_qual: str,
                       attr: str, _seen=None) -> Tuple[str, str]:
        """The MRO class whose methods assign ``self.<attr>`` — the
        canonical owner for lock identity (a base-class lock acquired
        from two subclasses is ONE lock)."""
        if _seen is None:
            _seen = set()
        if (rel, cls_qual) in _seen or len(_seen) > 32:
            return rel, cls_qual
        _seen.add((rel, cls_qual))
        c = self.class_fact(rel, cls_qual)
        if c is None:
            return rel, cls_qual
        if attr in c["attrs"]:
            return rel, cls_qual
        for base in c["bases"]:
            hit = self.resolve_class(rel, base)
            if hit is None:
                continue
            r2, q2 = self.defining_class(hit[0], hit[1], attr, _seen)
            c2 = self.class_fact(r2, q2)
            if c2 is not None and attr in c2["attrs"]:
                return r2, q2
        return rel, cls_qual

    # -- edges + summaries -------------------------------------------------
    def edges(self, key: str) -> List[Tuple[int, str, Optional[str]]]:
        """Resolved call edges of one def: (line, text, target-key)."""
        cached = self._edges.get(key)
        if cached is not None:
            return cached
        d = self.def_fact(key)
        out: List[Tuple[int, str, Optional[str]]] = []
        if d is not None:
            rel, qual = self.split(key)
            for line, text in d["calls"]:
                out.append((line, text, self.resolve(rel, qual, text)))
        self._edges[key] = out
        return out

    def summarize(self, key: str, tag: str,
                  direct: Callable[[str], Dict[str, int]],
                  follow: Callable[[str], bool],
                  edge_ok: Optional[Callable[[str, int], bool]] = None,
                  ) -> Dict[str, tuple]:
        """Per-def hazard summary ``{name: (line, via_key|None)}``.

        ``direct(key)`` yields the def's own hazards (name -> line);
        ``follow(target_key)`` gates which resolved edges propagate;
        ``edge_ok(key, line)`` (optional) drops individual CALL SITES
        from propagation — the seam that lets a pass honor an
        ``analysis-ok(<pass>)`` annotation on an intermediate sync call
        (e.g. a flag-gated legacy path) without silencing the helper
        for every other caller.  One witness step per hazard; chains
        come from ``chain()``.  Memoized per tag; cycles contribute
        nothing on the back edge (members still see each other's
        forward summaries).  Callers must pass a consistent
        direct/follow/edge_ok triple per tag."""
        memo = self._memos.setdefault(tag, {})

        def go(k: str, stack: set, depth: int) -> Dict[str, tuple]:
            if k in memo:
                return memo[k]
            if k in stack or depth > _SUMMARY_DEPTH:
                return {}
            out = {n: (ln, None) for n, ln in direct(k).items()}
            stack.add(k)
            for line, _text, tgt in self.edges(k):
                if tgt is None or tgt == k or not follow(tgt):
                    continue
                if edge_ok is not None and not edge_ok(k, line):
                    continue
                for n in go(tgt, stack, depth + 1):
                    out.setdefault(n, (line, tgt))
            stack.discard(k)
            memo[k] = out
            return out

        return go(key, set(), 0)

    def chain(self, key: str, hazard: str, tag: str,
              direct: Callable[[str], Dict[str, int]],
              follow: Callable[[str], bool],
              edge_ok: Optional[Callable[[str, int], bool]] = None,
              ) -> List[Tuple[str, str, int]]:
        """Witness chain for a summarized hazard:
        ``[(rel, qual, line), ...]`` from ``key`` down to the def
        making the direct hazardous call."""
        out: List[Tuple[str, str, int]] = []
        k: Optional[str] = key
        for _ in range(_SUMMARY_DEPTH + 1):
            if k is None:
                break
            s = self.summarize(k, tag, direct, follow, edge_ok)
            if hazard not in s:
                break
            line, nxt = s[hazard]
            rel, qual = self.split(k)
            out.append((rel, qual, line))
            k = nxt
        return out


def build_graph(index) -> CallGraph:
    """Extract (or cache-load) facts for every module in the index and
    assemble the graph.  ``index`` is a ProjectIndex; its optional
    ``cache_dir`` enables the persisted facts cache."""
    t0 = time.perf_counter()
    cache = None
    cache_dir = getattr(index, "cache_dir", None)
    if cache_dir:
        cache = FactsCache(cache_dir)
    hits = misses = 0
    facts_by_rel: Dict[str, dict] = {}
    for mod in index.modules():
        key = getattr(mod, "stat_key", None)
        if mod.rel in getattr(index, "overlay", {}):
            key = None                # staged content: never cached
        facts = cache.get(mod.rel, key) if cache else None
        if facts is None:
            facts = extract_facts(mod)
            misses += 1
            if cache is not None:
                cache.put(mod.rel, key, facts)
        else:
            hits += 1
        facts_by_rel[mod.rel] = facts
    if cache is not None:
        cache.save()
    stats = {"files": len(facts_by_rel), "cache_hits": hits,
             "cache_misses": misses,
             "build_ms": round((time.perf_counter() - t0) * 1e3, 2)}
    return CallGraph(facts_by_rel, stats)
