"""Framework core: shared walker, findings model, suppression grammar.

Every pass consumes a :class:`ProjectIndex` — each file is read and
parsed exactly once per run, however many passes look at it — and
returns :class:`Finding`s.  The runner applies the suppression grammar
and times each pass (the per-pass wall time rides in ``--json`` so
tier-1 can assert the whole sweep stays under budget).

Suppression grammar (one true spelling, one legacy alias):

    # analysis-ok(<pass>): <reason>
    # analysis-ok(<pass>, <pass2>): <reason>     (one line, two passes)
    # blocking-ok: <reason>                      (alias for async_blocking)

The comment lives on the finding line or the line above; the reason is
mandatory — an annotation that doesn't say WHY the hazard is acceptable
is itself a finding waiting to happen, so a bare marker suppresses
nothing.
"""
from __future__ import annotations

import ast
import os
import re
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: default analysis scope: the whole product tree.
DEFAULT_ROOTS: Tuple[str, ...] = ("yugabyte_db_tpu",)

_SUPPRESS_RE = re.compile(
    r"analysis-ok\(\s*([\w*]+(?:\s*,\s*[\w*]+)*)\s*\)\s*:\s*(\S)")
#: legacy alias kept so every pre-framework `blocking-ok:` annotation
#: (and tests/test_check_blocking.py) keeps working unmodified.
_ALIASES = {"async_blocking": re.compile(r"blocking-ok\s*:\s*(\S)")}


@dataclass
class Finding:
    """One hazard: file:line + pass id + message + fix hint.

    ``detail`` is the machine-usable core of the finding (e.g. the
    offending call's dotted name) — the check_blocking shim and tests
    key on it without parsing the prose."""

    path: str          # repo-relative
    line: int
    pass_id: str
    message: str
    detail: str = ""
    hint: str = ""

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "pass": self.pass_id,
                "message": self.message, "detail": self.detail,
                "hint": self.hint}

    def format(self) -> str:
        h = f"  [fix: {self.hint}]" if self.hint else ""
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}{h}"


@dataclass
class ModuleInfo:
    """One parsed source file, shared by every pass."""

    path: str                     # absolute
    rel: str                      # repo-relative (the Finding.path form)
    source: str
    lines: List[str]
    tree: Optional[ast.Module]    # None on syntax error
    parse_error: Optional[str] = None
    #: (mtime_ns, size) at read time — the facts-cache key; None for
    #: overlay content (staged bytes have no stable on-disk identity)
    stat_key: Optional[Tuple[int, int]] = None


class ProjectIndex:
    """Parse-once file index over the analysis roots.

    ``modules()`` walks the roots; ``module(rel)`` parses any repo file
    on demand (flag_drift reads bench.py / profile scripts / tests this
    way without widening every other pass's scope).  ``call_graph()``
    lazily builds the shared interprocedural graph; with ``cache_dir``
    set, its per-file extraction facts persist across runs keyed on
    (path, mtime, size) so a repeat run re-walks only changed files."""

    def __init__(self, base: str, roots: Sequence[str] = DEFAULT_ROOTS,
                 overlay: Optional[Dict[str, str]] = None,
                 cache_dir: Optional[str] = None):
        self.base = os.path.abspath(base)
        self.roots = tuple(roots)
        #: rel path -> source text that REPLACES the on-disk file (the
        #: pre-commit hook overlays staged INDEX content so a partially
        #: staged file is checked against the bytes being committed)
        self.overlay = dict(overlay or {})
        self.cache_dir = cache_dir
        self._cache: Dict[str, Optional[ModuleInfo]] = {}
        self._modules: Optional[List[ModuleInfo]] = None
        self._graph = None

    def module(self, rel: str) -> Optional[ModuleInfo]:
        if rel in self._cache:
            return self._cache[rel]
        path = os.path.join(self.base, rel)
        mi: Optional[ModuleInfo] = None
        stat_key = None
        if rel in self.overlay:
            src = self.overlay[rel]
        else:
            try:
                # stat BEFORE read: if a writer lands between the two,
                # the key describes the older content and the next run
                # simply misses — the reverse order could persist facts
                # of the old bytes under the new key, a permanently
                # stale cache entry
                st = os.stat(path)
                stat_key = (st.st_mtime_ns, st.st_size)
                with open(path, encoding="utf-8") as f:
                    src = f.read()
            except OSError:
                self._cache[rel] = None
                return None
        try:
            tree = ast.parse(src, filename=path)
            err = None
        except SyntaxError as e:
            tree, err = None, str(e)
        mi = ModuleInfo(path=path, rel=rel, source=src,
                        lines=src.splitlines(), tree=tree, parse_error=err,
                        stat_key=stat_key)
        self._cache[rel] = mi
        return mi

    def call_graph(self):
        """The shared interprocedural call graph (built once per run,
        however many passes consume it)."""
        if self._graph is None:
            from .callgraph import build_graph
            self._graph = build_graph(self)
        return self._graph

    def modules(self) -> List[ModuleInfo]:
        # every pass calls this; the tree walk is memoized alongside
        # the per-file parses (one run = one traversal, many readers)
        if self._modules is not None:
            return self._modules
        out: List[ModuleInfo] = []
        for root in self.roots:
            rootp = os.path.join(self.base, root)
            if os.path.isfile(rootp) and rootp.endswith(".py"):
                mi = self.module(os.path.relpath(rootp, self.base))
                if mi is not None:
                    out.append(mi)
                continue
            for dirpath, dirs, files in os.walk(rootp):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                for fn in sorted(files):
                    if not fn.endswith(".py"):
                        continue
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          self.base)
                    mi = self.module(rel)
                    if mi is not None:
                        out.append(mi)
        self._modules = out
        return out


class AnalysisPass:
    """Base class: subclasses set ``id``/``title``/``hint`` and
    implement ``run(index) -> [Finding]`` returning RAW findings — the
    runner applies suppression, so a pass never needs to know the
    grammar."""

    id: str = ""
    title: str = ""
    hint: str = ""

    def run(self, index: ProjectIndex) -> List[Finding]:
        raise NotImplementedError

    def finding(self, module: ModuleInfo, line: int, message: str,
                detail: str = "", hint: Optional[str] = None) -> Finding:
        return Finding(path=module.rel, line=line, pass_id=self.id,
                       message=message, detail=detail,
                       hint=self.hint if hint is None else hint)


# --- suppression ----------------------------------------------------------

def _line_suppresses(text: str, pass_id: str) -> bool:
    m = _SUPPRESS_RE.search(text)
    if m:
        ids = {p.strip() for p in m.group(1).split(",")}
        if pass_id in ids or "*" in ids:
            return True
    alias = _ALIASES.get(pass_id)
    return bool(alias and alias.search(text))


def is_suppressed(module: ModuleInfo, line: int, pass_id: str) -> bool:
    """True when the finding line or the line above carries a matching
    annotation (both spots allowed: long lines push the comment up)."""
    here = module.lines[line - 1] if 0 < line <= len(module.lines) else ""
    above = module.lines[line - 2] if line >= 2 else ""
    return (_line_suppresses(here, pass_id)
            or _line_suppresses(above, pass_id))


# --- shared AST helpers ---------------------------------------------------

def call_name(node: ast.Call) -> str:
    """Dotted name of a call target ('time.sleep', 'open', ...)."""
    f = node.func
    parts: List[str] = []
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return ".".join(reversed(parts))


_LOCKISH_RE = re.compile(r"(?:^|_)(?:lock|mutex|mu|rlock)s?$",
                         re.IGNORECASE)


def is_lockish(expr: ast.expr) -> bool:
    """Does a `with` context expression look like a lock?  Terminal
    name matching (self._lock, peer.apply_lock, LOCK, threading.Lock())
    — deliberately name-based: the analyzer runs without imports."""
    e = expr
    if isinstance(e, ast.Call):
        name = call_name(e)
        if name.endswith(("Lock", "RLock", "Condition", "Semaphore")):
            return True
        e = e.func
    if isinstance(e, ast.Attribute):
        return bool(_LOCKISH_RE.search(e.attr))
    if isinstance(e, ast.Name):
        return bool(_LOCKISH_RE.search(e.id))
    return False


def terminal_attr(expr: ast.expr) -> Optional[str]:
    """`self.tablet.flush` -> 'flush'; bare `flush` -> 'flush'."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


# --- runner ---------------------------------------------------------------

def run_analysis(index: ProjectIndex,
                 passes: Iterable[AnalysisPass]) -> dict:
    """Run passes over the index; returns the report dict that is also
    the ``--json`` schema:

    {"passes": [{"id", "title", "findings": N, "suppressed": N,
                 "wall_ms": F}],
     "findings": [finding dicts...],          # unsuppressed only
     "suppressions": {pass_id: N},            # the tally bench.py diffs
     "total_findings": N, "total_suppressed": N, "wall_ms": F,
     "parse_errors": [{"path", "error"}]}
    """
    report: dict = {"passes": [], "findings": [], "suppressions": {},
                    "parse_errors": []}
    seen_errors = set()
    total_ms = 0.0
    for p in passes:
        t0 = time.perf_counter()
        raw = p.run(index)
        kept: List[Finding] = []
        nsup = 0
        for f in raw:
            mod = index.module(f.path)
            if mod is not None and is_suppressed(mod, f.line, f.pass_id):
                nsup += 1
            else:
                kept.append(f)
        wall_ms = (time.perf_counter() - t0) * 1e3
        total_ms += wall_ms
        kept.sort(key=lambda f: (f.path, f.line))
        report["passes"].append({
            "id": p.id, "title": p.title, "findings": len(kept),
            "suppressed": nsup, "wall_ms": round(wall_ms, 2)})
        report["suppressions"][p.id] = nsup
        report["findings"].extend(f.to_dict() for f in kept)
    for rel, mi in index._cache.items():
        if mi is not None and mi.parse_error and rel not in seen_errors:
            seen_errors.add(rel)
            report["parse_errors"].append({"path": rel,
                                           "error": mi.parse_error})
    report["total_findings"] = len(report["findings"])
    report["total_suppressed"] = sum(report["suppressions"].values())
    report["wall_ms"] = round(total_ms, 2)
    if index._graph is not None:
        report["callgraph"] = dict(index._graph.stats)
    return report
