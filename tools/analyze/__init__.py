"""tools/analyze — multi-pass static analysis for event-loop, JAX-kernel
and concurrency hazards.

The hazard classes this repo keeps re-growing are mechanical and
AST-checkable: a blocking call or lock-held ``await`` on the one event
loop freezes admission and Raft heartbeats for the whole server; a
host-sync or shape-dependent branch inside a jitted kernel silently
destroys the compile-once property the bench numbers depend on; a flag
that drifts between definition and use lies to operators; an attribute
mutated from both an executor thread and the event loop is a data race.

Layout:

- ``core``       shared walker (one parse per file), findings model,
                 the ``analysis-ok(<pass>): <reason>`` suppression
                 grammar (``blocking-ok`` kept as an alias), runner
                 with per-pass wall time.
- ``passes/``    one module per pass; ``passes.ALL_PASSES`` is the
                 registry.
- ``run``        CLI: human output or ``--json`` (schema consumed by
                 tests/test_analysis.py and bench.py's WARN tail).

See ANALYSIS.md at the repo root for the pass catalog, the suppression
grammar, and how to add a pass.
"""
from .core import (AnalysisPass, Finding, ModuleInfo, ProjectIndex,
                   is_suppressed, run_analysis)
from .passes import ALL_PASSES, get_pass

__all__ = ["AnalysisPass", "Finding", "ModuleInfo", "ProjectIndex",
           "is_suppressed", "run_analysis", "ALL_PASSES", "get_pass"]
