"""Pass: the sst_format_version gate must not be bypassable.

The v2 columnar block format is gated by the ``sst_format_version``
runtime flag, resolved in exactly one place
(``storage/sst.py resolve_format_version``) so that flag value 1
reproduces the pre-v2 bytes everywhere. The gate drifts the moment any
writer hardcodes the new version instead of resolving the flag:

1. ``format_version=2`` passed as a LITERAL to ANY call in product
   code — an SstWriter call site that would emit v2 even when the flag
   says 1.
2. ``version=2`` passed as a literal to a ``serialize``/
   ``serialize_parts`` call (the block serializer's parameter name).
   The bare ``version`` kwarg is common in unrelated APIs
   (TableSchema(version=...)), so it only counts on serializer
   callees.
3. A literal ``2`` compared against or assigned around the resolver is
   fine; only explicit version-selecting call arguments are flagged.

Pinning the OLD format (``format_version=1`` — the baseline compaction
path does this deliberately) is always allowed: it can only ever make
output MORE compatible, never leak v2 past the flag.

tests/ are out of scope (they construct v2 blocks directly to test the
codec), as is storage/sst.py itself (the resolver's home).
"""
from __future__ import annotations

import ast
from typing import List

from ..core import AnalysisPass, Finding, ProjectIndex

#: kwargs that select an on-disk version: `format_version` anywhere;
#: the generic `version` only on serializer callees (other APIs use
#: `version` for schema versions etc.)
_SERIALIZER_NAMES = {"serialize", "serialize_parts"}
#: the resolver's home — the one module allowed to know the number
_ALLOWED = ("yugabyte_db_tpu/storage/sst.py",
            "yugabyte_db_tpu/storage/columnar.py",
            "yugabyte_db_tpu/storage/lane_codec.py")


def _callee_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


class FormatGatePass(AnalysisPass):
    id = "format_gate"
    title = "sst_format_version gate drift"
    hint = ("resolve the on-disk format through the sst_format_version "
            "flag (storage/sst.py resolve_format_version) instead of "
            "hardcoding the new version; pinning format_version=1 is "
            "always allowed")

    def run(self, index: ProjectIndex) -> List[Finding]:
        out: List[Finding] = []
        for mi in index.modules():
            if mi.tree is None or mi.rel.replace("\\", "/") in _ALLOWED:
                continue
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if kw.arg == "shred_cols" \
                            and _callee_name(node) in _SERIALIZER_NAMES:
                        # the doc_shred_enabled writer gate lives in
                        # SstWriter: a serializer call site feeding a
                        # non-empty literal shred_cols would emit
                        # shredded lanes even when the flag says off.
                        # (SstWriter(shred_cols=...) is always fine —
                        # the constructor resolves the flag.)
                        v = kw.value
                        if (isinstance(v, (ast.List, ast.Tuple, ast.Set))
                                and v.elts) or (
                                isinstance(v, ast.Constant)
                                and v.value not in (None, ())):
                            out.append(Finding(
                                path=mi.rel, line=node.lineno,
                                pass_id=self.id,
                                message=("literal `shred_cols` on a "
                                         "serializer call bypasses the "
                                         "doc_shred_enabled writer "
                                         "gate (SstWriter resolves "
                                         "the flag)"),
                                detail="shred_cols literal",
                                hint=self.hint))
                        continue
                    if kw.arg != "format_version" and not (
                            kw.arg == "version"
                            and _callee_name(node) in _SERIALIZER_NAMES):
                        continue
                    v = kw.value
                    if isinstance(v, ast.Constant) \
                            and isinstance(v.value, int) \
                            and v.value >= 2:
                        out.append(Finding(
                            path=mi.rel, line=node.lineno,
                            pass_id=self.id,
                            message=(f"hardcoded on-disk format "
                                     f"`{kw.arg}={v.value}` bypasses "
                                     "the sst_format_version flag gate"),
                            detail=f"{kw.arg}={v.value}",
                            hint=self.hint))
        return out


PASS = FormatGatePass()
