"""Pass: un-awaited coroutine calls and fire-and-forget tasks.

Two hazards with the same shape — a discarded awaitable:

1. A coroutine call whose result is thrown away as a bare expression
   statement: calling an ``async def`` returns a coroutine object;
   discarding it means the body NEVER RUNS (python warns "coroutine was
   never awaited" only at GC time, far from the call site).
2. ``asyncio.create_task(...)`` / ``ensure_future(...)`` whose handle
   is immediately discarded: the loop holds only a weak set of tasks,
   so the task can be garbage-collected mid-flight and an exception
   inside it is never observed.  Keeping the handle (assignment,
   ``tasks.append(...)``) or chaining ``.add_done_callback(...)``
   (which makes the statement's terminal call ``add_done_callback``)
   both escape the flag; so does ``tg.create_task(...)`` on a TaskGroup
   (strong references, structured exception propagation) — only
   module-/loop-receiver spawners flag.

Coroutine-ness is resolved only where the evidence is local and
unambiguous — stdlib sync twins (``StreamWriter.write``,
``Executor.shutdown``) share leaf names with tree-local async defs, so
bare leaf-name matching drowns in false positives.  Flagged forms:

- ``self.m(...)`` where the ENCLOSING CLASS defines ``async def m``;
- a bare ``f(...)`` where the same module defines ``async def f`` at
  module level (and no sync ``def f`` anywhere in the module);
- ``asyncio.gather/wait/wait_for/shield/sleep`` results discarded.

Dotted cross-module calls are out of scope (documented recall
tradeoff; ANALYSIS.md known limits).
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import AnalysisPass, Finding, ModuleInfo, ProjectIndex, call_name

#: builtin awaitable producers whose discarded result is always a bug
_BUILTIN_AWAITABLES = {
    "asyncio.gather", "asyncio.wait", "asyncio.wait_for",
    "asyncio.shield", "asyncio.sleep",
}

#: task spawners whose discarded handle is a fire-and-forget task
_SPAWNERS = {"create_task", "ensure_future"}


def _loop_spawner(name: str, leaf: str) -> bool:
    """True when the spawner receiver is the module / an event loop —
    the forms whose tasks live in the loop's WEAK set.  A TaskGroup's
    ``tg.create_task(...)`` holds a strong reference and propagates
    exceptions, so discarding that handle is the documented pattern and
    must not flag."""
    prefix = name[:-len(leaf)].rstrip(".")
    return (prefix in ("", "asyncio", "aio", "loop")
            or prefix.endswith((".loop", "_loop")))


class UnawaitedCoroutinePass(AnalysisPass):
    id = "unawaited_coroutine"
    title = "un-awaited coroutine / fire-and-forget task"
    hint = ("await it; or keep the task handle and chain "
            ".add_done_callback so failures surface")

    def run(self, index: ProjectIndex) -> List[Finding]:
        out: List[Finding] = []
        for mod in index.modules():
            if mod.tree is None:
                continue
            mod_async: Set[str] = set()
            mod_sync: Set[str] = set()
            for node in mod.tree.body:
                if isinstance(node, ast.AsyncFunctionDef):
                    mod_async.add(node.name)
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.FunctionDef):
                    mod_sync.add(node.name)
            bare_async = mod_async - mod_sync
            self._scan_body(mod, mod.tree.body, None, bare_async, out)
        return out

    # ------------------------------------------------------------------
    def _scan_body(self, mod: ModuleInfo, body, cls_async: Optional[Set[str]],
                   bare_async: Set[str], out: List[Finding]) -> None:
        """Recursive statement walk that RE-SCOPES at every ClassDef —
        a class nested inside a method gets its OWN async-method set
        (ast.walk would carry the outer class's set into it and flag
        the inner class's sync self-calls)."""
        for node in body:
            if isinstance(node, ast.ClassDef):
                methods = {n.name for n in node.body
                           if isinstance(n, ast.AsyncFunctionDef)}
                sync = {n.name for n in node.body
                        if isinstance(n, ast.FunctionDef)}
                self._scan_body(mod, node.body, methods - sync,
                                bare_async, out)
                continue
            if isinstance(node, ast.Expr) and \
                    isinstance(node.value, ast.Call):
                self._check_call(mod, node, cls_async, bare_async, out)
            # recurse into child STATEMENTS (body/orelse/finally of
            # compound statements, nested function defs) with the SAME
            # class scope — a nested sync def still closes over the
            # enclosing `self`
            self._scan_body(
                mod, [c for c in ast.iter_child_nodes(node)
                      if isinstance(c, (ast.stmt, ast.ExceptHandler,
                                        ast.match_case))],
                cls_async, bare_async, out)

    def _check_call(self, mod: ModuleInfo, stmt: ast.Expr,
                    cls_async: Optional[Set[str]], bare_async: Set[str],
                    out: List[Finding]) -> None:
        call = stmt.value
        name = call_name(call)
        leaf = name.rsplit(".", 1)[-1]
        if leaf in _SPAWNERS and _loop_spawner(name, leaf):
            out.append(self.finding(
                mod, stmt.lineno,
                f"`{name}(...)` handle discarded — the loop keeps only "
                f"a weak reference, so the task can be GC'd mid-flight "
                f"and its exception is never observed",
                detail=name))
            return
        is_self_method = (isinstance(call.func, ast.Attribute)
                          and isinstance(call.func.value, ast.Name)
                          and call.func.value.id == "self"
                          and cls_async is not None
                          and call.func.attr in cls_async)
        is_bare = (isinstance(call.func, ast.Name)
                   and call.func.id in bare_async)
        if name in _BUILTIN_AWAITABLES or is_self_method or is_bare:
            out.append(self.finding(
                mod, stmt.lineno,
                f"coroutine `{name}(...)` is never awaited — the call "
                f"builds a coroutine object and discards it; the body "
                f"never runs",
                detail=name))


PASS = UnawaitedCoroutinePass()
