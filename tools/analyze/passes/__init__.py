"""Pass registry: one module per pass, ``PASS`` is the singleton.

Adding a pass (see ANALYSIS.md):
1. subclass :class:`analyze.core.AnalysisPass` in a new module here,
2. export a ``PASS`` instance and add it to ``ALL_PASSES``,
3. give tests/test_analysis.py a true-positive, a suppressed, and a
   clean-negative fixture for it,
4. run ``python tools/analyze/run.py`` and fix or annotate what it
   finds — the whole-tree tier-1 sweep must stay at zero.
"""
from . import (async_blocking, cache_key_completeness, flag_drift,
               format_gate, jit_hazards, layering, lock_held_await,
               lock_order, numeric_exactness, refusal_flow,
               resource_balance, shared_state_races,
               trace_discipline, unawaited_coroutine, wire_drift)

ALL_PASSES = (
    async_blocking.PASS,
    lock_held_await.PASS,
    jit_hazards.PASS,
    flag_drift.PASS,
    shared_state_races.PASS,
    unawaited_coroutine.PASS,
    format_gate.PASS,
    layering.PASS,
    lock_order.PASS,
    resource_balance.PASS,
    trace_discipline.PASS,
    refusal_flow.PASS,
    cache_key_completeness.PASS,
    wire_drift.PASS,
    numeric_exactness.PASS,
)

_BY_ID = {p.id: p for p in ALL_PASSES}


def get_pass(pass_id: str):
    try:
        return _BY_ID[pass_id]
    except KeyError:
        raise KeyError(
            f"unknown pass {pass_id!r}; known: {sorted(_BY_ID)}") from None


__all__ = ["ALL_PASSES", "get_pass"]
