"""Pass: acquire/release balance for leases, handles and gauges.

The bypass engine's isolation numbers rest on a refcount discipline:
``LsmStore.pin_ssts`` defers physical SST deletion until the lease is
released, so a leaked ``SstLease`` pins compacted gigabytes FOREVER —
no crash, no error, just disk that never comes back (the PR-7 lease
sweeper only covers process death, not a live leak).  The same shape
applies to raw ``open``/``mmap.mmap`` handles held by long-running
server code, and to +=/-= gauge pairs (in-flight counters) whose early
return skews admission decisions from then on.

Flow-sensitive, per function, per acquired name:

- ACQUIRE: ``x = <recv>.pin_ssts(...)`` (released by ``x.release()``),
  ``x = open/io.open/os.fdopen/mmap.mmap(...)`` (released by
  ``x.close()``).  A ``with ... as x`` acquisition is owned by the
  context manager and exempt; ``with x:`` / ``with
  contextlib.closing(x):`` later counts as a release.
- OWNERSHIP TRANSFER: ``return``/``yield`` of the binding is a
  transfer on THAT exit (other exits still must release); a binding
  that escapes the function — stored into an attribute/subscript/
  container, passed as a call argument, captured by a nested
  def/lambda, or rebound — disowns the whole analysis (the receiver's
  balance is its own function's problem).  A ``pin_ssts`` result that
  is DISCARDED outright is always a leak.
- EXITS: per the lease contract, every acquire must reach a release on
  all NON-RAISING exits: a ``return`` between acquire and release, or
  falling off the end of the function still holding, is a finding.
  Raising exits are exempt (callers of raising code clean up via the
  crash sweep / context managers); a release inside a ``finally``
  covers every exit of its try, returns included.
- GAUGES: when ONE function both increments and decrements the same
  ``+=``/``-=`` target (``self._inflight += 1 ... -= 1``), a return
  between the two that skips the decrement is flagged.  Functions that
  only increment (monotonic stats counters like KEY_REBUILD_STATS)
  are not paired and never flag.  Only attribute/subscript targets
  participate (a bare local counter dies with the frame — parser depth
  counters are not gauges), and the flagged return must jump OVER a
  decrement later in source order (a return behind every decrement —
  cache-eviction accounting — skips nothing).

Known limits (by design): conditional aliasing and cross-function
hand-off protocols other than the escape forms above are not tracked;
loops are walked once (no fixpoint); generators are skipped wholesale
(their frames outlive any lexical exit).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import (AnalysisPass, Finding, ModuleInfo, ProjectIndex,
                    call_name)

#: leaf method names that acquire (matched on any receiver — the
#: receiver's type is unknowable without imports) -> release method
_ACQUIRE_METHODS = {"pin_ssts": ("release", "close")}
#: dotted callables that acquire -> release method
_ACQUIRE_CALLS = {"open": ("close",), "io.open": ("close",),
                  "os.fdopen": ("close",), "mmap.mmap": ("close",)}
#: acquire calls whose DISCARDED result is always a leak (a dropped
#: file handle is closed by CPython's refcounting; a dropped lease
#: pins SSTs until process exit)
_NEVER_DISCARD = {"pin_ssts"}

_HELD, _RELEASED = "held", "released"
_COMPOUND = (ast.If, ast.Try, ast.For, ast.AsyncFor, ast.While,
             ast.With, ast.AsyncWith)


def _acquire_info(call: ast.Call) -> Optional[Tuple[str, tuple]]:
    """(kind, release-methods) when this call acquires a resource."""
    name = call_name(call)
    if not name:
        return None
    if name in _ACQUIRE_CALLS:
        return name, _ACQUIRE_CALLS[name]
    leaf = name.rsplit(".", 1)[-1]
    if leaf in _ACQUIRE_METHODS and "." in name:
        return leaf, _ACQUIRE_METHODS[leaf]
    return None


class _Tracker:
    """Flow walk for ONE acquisition: reports non-raising exits that
    skip the release.  ``leaf_*`` callbacks classify simple
    statements; compound statements are structured here so a release
    in one branch never masks a leak in the other."""

    def __init__(self, var: Optional[str], leaf_release, leaf_escape,
                 returns_transfer):
        self.var = var
        self.leaf_release = leaf_release     # leaf stmt -> bool
        self.leaf_escape = leaf_escape       # leaf stmt -> bool
        self.returns_transfer = returns_transfer   # Return -> bool
        self.escaped = False
        self.leaks: List[Tuple[int, str]] = []

    def block(self, stmts, state: str, fin: bool) -> str:
        for s in stmts:
            if self.escaped:
                return _RELEASED
            state = self.stmt(s, state, fin)
        return state

    def stmt(self, s: ast.stmt, state: str, fin: bool) -> str:
        if isinstance(s, ast.Return):
            if not self.returns_transfer(s) \
                    and state == _HELD and not fin:
                self.leaks.append((s.lineno, "return"))
            return _RELEASED          # flow ends here; statements after
            #                           this exit (sibling branches,
            #                           fall-through) judge themselves
        if isinstance(s, ast.Raise):
            return _RELEASED          # raising exits are exempt AND
            #                           terminate the path
        if isinstance(s, ast.If):
            s1 = self.block(s.body, state, fin)
            s2 = self.block(s.orelse, state, fin)
            if s1 == s2:
                return s1
            return _HELD if _HELD in (s1, s2) else state
        if isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
            s1 = self.block(s.body, state, fin)
            s2 = self.block(s.orelse, s1, fin)
            return _HELD if _HELD in (s1, s2, state) else state
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                if self._with_releases(item.context_expr):
                    self.block(s.body, _RELEASED, fin)
                    return _RELEASED
            return self.block(s.body, state, fin)
        if isinstance(s, ast.Try):
            fin_rel = any(self._contains_release(fs)
                          for fs in s.finalbody)
            covers = fin or fin_rel
            st = self.block(s.body, state, covers)
            st = self.block(s.orelse, st, covers)
            for h in s.handlers:
                self.block(h.body, state, covers)
            st = self.block(s.finalbody, st, fin)
            return _RELEASED if fin_rel else st
        # leaf statements (incl. nested defs: capture check)
        if self.leaf_escape(s):
            self.escaped = True
            return _RELEASED
        if self.leaf_release(s):
            return _RELEASED
        return state

    def _contains_release(self, s: ast.stmt) -> bool:
        if isinstance(s, _COMPOUND):
            kids = [c for c in ast.iter_child_nodes(s)
                    if isinstance(c, (ast.stmt, ast.ExceptHandler))]
            return any(self._contains_release(k) for k in kids)
        if isinstance(s, ast.ExceptHandler):
            return any(self._contains_release(k) for k in s.body)
        return self.leaf_release(s)

    def _with_releases(self, expr: ast.expr) -> bool:
        if self.var is None:
            return False
        if isinstance(expr, ast.Name) and expr.id == self.var:
            return True
        if isinstance(expr, ast.Call) and expr.args:
            a = expr.args[0]
            if isinstance(a, ast.Name) and a.id == self.var \
                    and call_name(expr).rsplit(".", 1)[-1] == "closing":
                return True
        return False


class ResourceBalancePass(AnalysisPass):
    id = "resource_balance"
    title = "unbalanced acquire/release (lease, handle or gauge leak)"
    hint = ("release on every non-raising exit (try/finally or a "
            "context manager), or hand the resource off explicitly "
            "(return it / store it on the owner)")

    def run(self, index: ProjectIndex) -> List[Finding]:
        from ..callgraph import iter_defs
        out: List[Finding] = []
        for mod in index.modules():
            if mod.tree is None:
                continue
            for _qual, _cls, node in iter_defs(mod.tree):
                self._scan_def(mod, node, out)
        return out

    # ------------------------------------------------------------------
    def _scan_def(self, mod: ModuleInfo, fn, out: List[Finding]) -> None:
        body = fn.body
        stmts = list(self._own_stmts(body))
        if any(isinstance(n, (ast.Yield, ast.YieldFrom))
               for s in stmts if not isinstance(
                   s, (ast.FunctionDef, ast.AsyncFunctionDef))
               for n in self._own_walk(s)):
            return    # generator frames outlive the walk; out of scope
        for s in stmts:
            acq = self._stmt_acquisition(s)
            if acq is not None:
                var, kind, rel_methods, line = acq
                self._check_resource(mod, body, s, var, kind,
                                     rel_methods, line, out)
        self._check_gauges(mod, body, stmts, out)

    @staticmethod
    def _own_walk(s: ast.AST):
        """ast.walk stopping at nested def/class/lambda boundaries."""
        stack = [s]
        while stack:
            n = stack.pop()
            yield n
            for c in ast.iter_child_nodes(n):
                if not isinstance(c, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    stack.append(c)

    @classmethod
    def _own_stmts(cls, body):
        """Every statement of the function EXCLUDING nested def/class
        bodies (they balance their own resources)."""
        for s in body:
            yield s
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            for c in ast.iter_child_nodes(s):
                if isinstance(c, ast.stmt):
                    yield from cls._own_stmts([c])
                elif isinstance(c, (ast.ExceptHandler, ast.match_case)):
                    yield from cls._own_stmts(c.body)

    @staticmethod
    def _stmt_acquisition(s: ast.stmt):
        """(var|None, kind, release_methods, line) when `s` acquires."""
        if isinstance(s, ast.Assign) and isinstance(s.value, ast.Call) \
                and len(s.targets) == 1 \
                and isinstance(s.targets[0], ast.Name):
            info = _acquire_info(s.value)
            if info is not None:
                return s.targets[0].id, info[0], info[1], s.lineno
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
            info = _acquire_info(s.value)
            if info is not None and info[0] in _NEVER_DISCARD:
                return None, info[0], info[1], s.lineno
        return None

    # ------------------------------------------------------------------
    def _check_resource(self, mod: ModuleInfo, body, acq_stmt,
                        var: Optional[str], kind: str, rel_methods,
                        line: int, out: List[Finding]) -> None:
        if var is None:
            out.append(self.finding(
                mod, line,
                f"`{kind}(...)` result discarded — the lease is never "
                f"released, so its pinned files leak until process "
                f"exit",
                detail=f"{kind}:discarded"))
            return
        release_names = set(rel_methods)

        def leaf_release(s: ast.stmt) -> bool:
            for n in self._own_walk(s):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in release_names \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.value.id == var:
                    return True
            return False

        def leaf_escape(s: ast.stmt) -> bool:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return any(isinstance(n, ast.Name) and n.id == var
                           for n in ast.walk(s))
            for n in self._own_walk(s):
                if isinstance(n, ast.Lambda) and any(
                        isinstance(m, ast.Name) and m.id == var
                        for m in ast.walk(n)):
                    return True
                if isinstance(n, (ast.Yield, ast.YieldFrom)) \
                        and n.value is not None \
                        and self._uses(n.value, var):
                    return True
                if isinstance(n, ast.Call):
                    if isinstance(n.func, ast.Attribute) \
                            and isinstance(n.func.value, ast.Name) \
                            and n.func.value.id == var:
                        continue      # method ON the resource: a use
                    for a in list(n.args) + [k.value for k in
                                             n.keywords]:
                        if isinstance(a, ast.Name) and a.id == var:
                            return True
                if isinstance(n, ast.Assign):
                    if self._uses(n.value, var) and any(
                            not isinstance(t, ast.Name)
                            for t in n.targets):
                        return True   # self.x = var / d[k] = var
                    if any(isinstance(t, ast.Name) and t.id == var
                           for t in n.targets) and n is not acq_stmt:
                        return True   # rebinding: aliasing, not ours
                if isinstance(n, (ast.List, ast.Tuple, ast.Set,
                                  ast.Dict)):
                    for elt in ast.iter_child_nodes(n):
                        if isinstance(elt, ast.Name) and elt.id == var:
                            return True
            return False

        def returns_transfer(s: ast.Return) -> bool:
            return s.value is not None and self._uses(s.value, var)

        tr = _Tracker(var, leaf_release, leaf_escape, returns_transfer)
        state = self._walk_from(tr, body, acq_stmt)
        if tr.escaped:
            return
        if state == _HELD:
            tr.leaks.append((line, "fall-through"))
        released_somewhere = any(leaf_release(s)
                                 for s in self._own_stmts(body))
        for leak_line, how in tr.leaks:
            if how == "return":
                msg = (f"`{var} = {kind}(...)` (line {line}) is not "
                       f"released on the return exit at line "
                       f"{leak_line}")
            elif released_somewhere:
                msg = (f"`{var} = {kind}(...)` is not released on the "
                       f"fall-through exit")
            else:
                msg = (f"`{var} = {kind}(...)` is never released on "
                       f"any path")
            out.append(self.finding(mod, leak_line, msg,
                                    detail=f"{kind}:{var}"))

    @staticmethod
    def _uses(expr: ast.expr, var: str) -> bool:
        return any(isinstance(n, ast.Name) and n.id == var
                   for n in ast.walk(expr))

    def _walk_from(self, tr: _Tracker, body, acq_stmt) -> str:
        """Evaluate the function body with the resource becoming HELD
        at ``acq_stmt``; statements before it are state-neutral."""
        armed = [False]
        orig_stmt = tr.stmt

        def stmt(s, state, fin):
            if s is acq_stmt:
                armed[0] = True
                return _HELD
            if not armed[0]:
                if isinstance(s, _COMPOUND):
                    return orig_stmt(s, state, fin)
                return state
            return orig_stmt(s, state, fin)

        tr.stmt = stmt
        return tr.block(body, _RELEASED, False)

    # ------------------------------------------------------------------
    def _check_gauges(self, mod: ModuleInfo, body, stmts,
                      out: List[Finding]) -> None:
        incs: Dict[str, ast.AugAssign] = {}
        decs: Dict[str, List[ast.AugAssign]] = {}
        for s in stmts:
            if not isinstance(s, ast.AugAssign):
                continue
            try:
                t = ast.unparse(s.target)
            except Exception:     # noqa: BLE001 — exotic target
                continue
            if isinstance(s.op, ast.Add):
                incs.setdefault(t, s)
            elif isinstance(s.op, ast.Sub):
                decs.setdefault(t, []).append(s)
        for t, inc in sorted(incs.items()):
            if t not in decs:
                continue          # monotonic counter: not a gauge
            if "." not in t and "[" not in t:
                continue          # bare local (parser depth counter
                #                   etc.): dies with the frame, cannot
                #                   drift anything
            dec_stmts = decs[t]

            def leaf_release(s: ast.stmt, _d=dec_stmts) -> bool:
                return any(c is d for d in _d
                           for c in self._own_walk(s))

            tr = _Tracker(None, leaf_release, lambda s: False,
                          lambda r: False)
            self._walk_from(tr, body, inc)
            last_dec = max(d.lineno for d in dec_stmts)
            for leak_line, how in tr.leaks:
                if how != "return":
                    continue      # fall-through without dec = the
                    #               inc/dec live in different branches
                if leak_line > last_dec:
                    continue      # every decrement is behind this
                    #               return: nothing was jumped over
                    #               (cache-eviction accounting, not an
                    #               in-flight pair)
                out.append(self.finding(
                    mod, leak_line,
                    f"gauge `{t}` incremented at line {inc.lineno} but "
                    f"the return at line {leak_line} skips the "
                    f"matching decrement — the counter drifts and "
                    f"every later admission decision inherits the "
                    f"skew",
                    detail=f"gauge:{t}"))


PASS = ResourceBalancePass()
