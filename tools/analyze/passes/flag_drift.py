"""Pass: drift between flag definitions (utils/flags.py) and use.

Four mechanical drift shapes:

1. DEFINED, NEVER READ — a ``DEFINE``/``DEFINE_RUNTIME`` whose name no
   product code or bench/profile script ever ``flags.get``s: dead
   operator surface that lies about being a knob.
2. READ, NEVER DEFINED — ``flags.get("name")`` of a name no DEFINE
   creates: a KeyError waiting for that code path.
3. DUPLICATE DEFINITION with a different default (``define`` returns
   the first registration, so the second default silently loses).
4. DOC DEFAULT MISMATCH — a ``(default X)`` claim in the flag's help
   text or the repo docs (COVERAGE.md / ANALYSIS.md / README.md) that
   disagrees with the actual default.

Dynamic reads through f-strings (``flags.get(f"sched_{lane}_depth")``)
are matched as regexes against the defined names; fully dynamic reads
(``flags.get(var)`` in the CLI's hot-flag tooling) are ignored — they
can't prove a specific flag is wired.  Reads in tests/ don't count: a
flag only a test touches is not wired into the product.
"""
from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from ..core import AnalysisPass, Finding, ModuleInfo, ProjectIndex, call_name

FLAGS_MODULE = os.path.join("yugabyte_db_tpu", "utils", "flags.py")
_DEFINE_FUNCS = {"DEFINE", "DEFINE_RUNTIME", "define_flag",
                 "REGISTRY.define", "flags.DEFINE", "flags.DEFINE_RUNTIME"}
_AUTO_FUNCS = {"DEFINE_AUTO", "flags.DEFINE_AUTO"}
_READ_METHODS = {"get", "on_change"}
_DOC_GLOBS = ("COVERAGE.md", "ANALYSIS.md", "README.md", "ROADMAP.md")
_DOC_DEFAULT_RE = r"`?%s`?\s*\(default[:\s]+([^)]+)\)"
# matches "default 5", "default: 5", "(default 5)", "default=5",
# "defaults to 9", "default is True" — the claimed value must LOOK like
# a value (number/bool/None/quoted) so prose like "the default backend"
# never false-positives
_HELP_DEFAULT_RE = re.compile(
    r"\bdefaults?\s*(?:(?:is|to)\s+)?[:=]?\s*"
    r"(-?[0-9][\w.\-]*|True|False|None|'[^']+'|\"[^\"]+\")",
    re.IGNORECASE)


def _literal(node: ast.expr):
    """Best-effort literal value; None when not statically evaluable
    (e.g. `16 * 1024 * 1024` — those skip the doc-mismatch check)."""
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _fstring_regex(node: ast.JoinedStr) -> Optional[str]:
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(re.escape(str(v.value)))
        else:
            parts.append(".*")
    return "^" + "".join(parts) + "$"


class FlagDriftPass(AnalysisPass):
    id = "flag_drift"
    title = "flag definition/use drift"
    hint = ("wire the flag, delete it, or annotate the DEFINE with "
            "`# analysis-ok(flag_drift): <reason>` if it is reserved")

    #: extra read scopes beyond the analysis roots: bench/profile
    #: scripts at the repo root use flags too.
    EXTRA_READ_GLOBS = ("*.py",)

    def run(self, index: ProjectIndex) -> List[Finding]:
        out: List[Finding] = []
        defs, autos = self._collect_definitions(index, out)
        reads, regexes = self._collect_reads(index, out, set(defs))
        for rx in regexes:
            pat = re.compile(rx)
            reads.update(n for n in defs if pat.match(n))
        # indirection fallback: a flag name appearing as ANY string
        # literal in product code (e.g. a `fraction_flag="..."` param
        # default that later reaches flags.get) counts as read — a
        # truly dead flag's name appears nowhere outside its DEFINE.
        unread = {n for n in defs if n not in reads}
        if unread:
            for mod in self._read_modules(index):
                if mod.tree is None or mod.rel == FLAGS_MODULE:
                    continue
                for node in ast.walk(mod.tree):
                    if isinstance(node, ast.Constant) \
                            and isinstance(node.value, str) \
                            and node.value in unread \
                            and mod.rel != defs[node.value][0].rel:
                        reads.add(node.value)
                        unread.discard(node.value)
                if not unread:
                    break
        for name, (mod, line, _default, _help) in sorted(defs.items()):
            if name not in reads and name not in autos:
                out.append(self.finding(
                    mod, line,
                    f"flag `{name}` is defined but never read by product "
                    f"code or bench/profile scripts",
                    detail=name))
        self._check_doc_defaults(index, defs, out)
        return out

    # --- definitions ------------------------------------------------------
    def _collect_definitions(self, index: ProjectIndex,
                             out: List[Finding]):
        defs: Dict[str, Tuple[ModuleInfo, int, object, str]] = {}
        autos: Set[str] = set()
        for mod in index.modules():
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = call_name(node)
                is_def = fname in _DEFINE_FUNCS
                is_auto = fname in _AUTO_FUNCS
                if not (is_def or is_auto):
                    continue
                if not (node.args and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                name = node.args[0].value
                default = (_literal(node.args[1])
                           if len(node.args) > 1 else None)
                help_txt = ""
                if len(node.args) > 2 and isinstance(node.args[2],
                                                     ast.Constant):
                    help_txt = str(node.args[2].value)
                for kw in node.keywords:
                    if kw.arg == "help" and isinstance(kw.value,
                                                       ast.Constant):
                        help_txt = str(kw.value.value)
                if is_auto:
                    autos.add(name)     # read via auto_flags()/promotion
                if name in defs:
                    prev = defs[name]
                    if default is not None and prev[2] is not None \
                            and prev[2] != default:
                        out.append(self.finding(
                            mod, node.lineno,
                            f"flag `{name}` re-defined with a different "
                            f"default ({default!r} vs {prev[2]!r} at "
                            f"{prev[0].rel}:{prev[1]}) — define() keeps "
                            f"the FIRST registration, this default "
                            f"silently loses",
                            detail=name,
                            hint="one DEFINE per flag; share it"))
                    continue
                defs[name] = (mod, node.lineno, default, help_txt)
        return defs, autos

    # --- reads ------------------------------------------------------------
    def _read_modules(self, index: ProjectIndex) -> List[ModuleInfo]:
        mods = list(index.modules())
        for pat in self.EXTRA_READ_GLOBS:
            for path in sorted(glob.glob(os.path.join(index.base, pat))):
                rel = os.path.relpath(path, index.base)
                mi = index.module(rel)
                if mi is not None:
                    mods.append(mi)
        return mods

    @staticmethod
    def _flag_aliases(mod: ModuleInfo) -> Set[str]:
        """Names the flags module is bound to in this module (`flags`,
        `_flags`, ...) — keeps dict-typed locals called `flags` from
        polluting the read scan."""
        aliases: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name == "flags":
                        aliases.add(a.asname or a.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.endswith(".flags") or a.name == "flags":
                        aliases.add((a.asname or a.name).split(".")[0])
        return aliases

    def _collect_reads(self, index: ProjectIndex, out: List[Finding],
                       defined: Set[str]):
        reads: Set[str] = set()
        regexes: Set[str] = set()
        for mod in self._read_modules(index):
            if mod.tree is None or mod.rel == FLAGS_MODULE:
                continue
            aliases = self._flag_aliases(mod)
            if not aliases:
                continue
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in (_READ_METHODS | {"set",
                                                                "reset"})
                        and node.args):
                    continue
                recv = node.func.value
                recv_name = recv.id if isinstance(recv, ast.Name) else ""
                if recv_name not in aliases and not (
                        isinstance(recv, ast.Attribute)
                        and recv.attr == "REGISTRY"):
                    continue
                arg = node.args[0]
                is_read = node.func.attr in _READ_METHODS
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    if is_read:
                        reads.add(arg.value)
                    if arg.value not in defined:
                        out.append(self.finding(
                            mod, node.lineno,
                            f"flag `{arg.value}` is "
                            f"{'read' if is_read else 'set'} here but "
                            f"never defined in utils/flags.py",
                            detail=arg.value,
                            hint="DEFINE it (or fix the typo)"))
                elif isinstance(arg, ast.JoinedStr) and is_read:
                    rx = _fstring_regex(arg)
                    if rx:
                        regexes.add(rx)
                # fully dynamic reads (Name arg) prove nothing; skip
        # set_flag("x", v) module-level helper calls
        for mod in self._read_modules(index):
            if mod.tree is None or mod.rel == FLAGS_MODULE:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) \
                        and call_name(node).split(".")[-1] == "set_flag" \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str) \
                        and node.args[0].value not in defined:
                    out.append(self.finding(
                        mod, node.lineno,
                        f"flag `{node.args[0].value}` is set here but "
                        f"never defined in utils/flags.py",
                        detail=node.args[0].value,
                        hint="DEFINE it (or fix the typo)"))
        return reads, regexes

    # --- doc defaults -----------------------------------------------------
    def _check_doc_defaults(self, index: ProjectIndex, defs,
                            out: List[Finding]) -> None:
        docs: List[Tuple[str, List[str]]] = []
        for fn in _DOC_GLOBS:
            path = os.path.join(index.base, fn)
            if os.path.isfile(path):
                with open(path, encoding="utf-8") as f:
                    docs.append((fn, f.read().splitlines()))
        for name, (mod, line, default, help_txt) in sorted(defs.items()):
            if default is None:
                continue
            claims: List[Tuple[str, int, str]] = []
            m = _HELP_DEFAULT_RE.search(help_txt)
            if m:
                claims.append((mod.rel, line, m.group(1)))
            rx = re.compile(_DOC_DEFAULT_RE % re.escape(name))
            for fn, lines in docs:
                for i, text in enumerate(lines, 1):
                    dm = rx.search(text)
                    if dm:
                        claims.append((fn, i, dm.group(1).strip()))
            for src, src_line, claim in claims:
                if not self._claim_matches(claim, default):
                    out.append(self.finding(
                        mod, line,
                        f"flag `{name}` default is {default!r} but "
                        f"{src}:{src_line} documents default "
                        f"`{claim}`",
                        detail=name,
                        hint="fix whichever side is wrong"))

    @staticmethod
    def _claim_matches(claim: str, default) -> bool:
        c = claim.strip().strip("`'\"")
        if c == str(default):
            return True
        try:
            return ast.literal_eval(c) == default
        except (ValueError, SyntaxError):
            return c.lower() == str(default).lower()


PASS = FlagDriftPass()
