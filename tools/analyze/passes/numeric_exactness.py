"""Pass: numeric-exactness contract — SUM/COUNT stay exact int64, and
zone-map float bounds are only consumed through the f32-widened
envelope.

The aggregate contract (ops/scan.py): SUM and COUNT are EXACT —
integer lanes accumulate in int64, float lanes quantize to int64
fixed-point first; floats never accumulate in float32 (5e8 rows of
1.0 in f32 saturates at 2**24 and silently stops counting).  Zone-map
bounds are stored as float32 minima/maxima of possibly-float64 data,
so a consumer comparing them EXACTLY can prune a block that actually
contains matching rows — every consumer must go through the
``_f32_widen`` one-ulp-outward envelope in ops/scan.py.  And
constant-table compilation (``compile_expr``) is positional: a second
compile in the same def without an explicit ``offset=`` re-reads the
FIRST expression's constants (the PR-12 consts-offset regression).

Rules (all taint-local to one def; under-approximate on missing
evidence — no finding without a dtype witness):

- R1 ``sum-dtype``: ``jnp.sum(x)`` / ``segment_sum(x, ...)`` with no
  ``dtype=`` where ``x``'s local assignment evidence shows a narrow
  integer/bool dtype (int8/16/32, bool) and never int64 — the
  accumulator inherits the narrow dtype and overflows.
- R2 ``zone-envelope``: an attribute read of ``.zmap`` in any module
  other than the envelope implementation (ops/scan.py) and the
  builders (storage/columnar.py, docstore/pushdown.py) — raw bounds
  must not leak past the widened envelope.
- R3 ``float-accumulator``: summing a value whose evidence shows an
  int/bool source cast through float32 (``x.astype(jnp.float32)``
  then summed) — exact counts silently become saturating f32 adds.
- R4 ``consts-offset``: a def calling ``compile_expr`` two or more
  times where any call after the first omits ``offset=`` — the
  second expression reads the first's constant table.

Suppress at the reported line:
``# analysis-ok(numeric_exactness): <reason>`` (the mosaic/pallas
kernels legitimately accumulate f32 inside bounded-row eligibility —
each such site carries its reason).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..core import AnalysisPass, Finding, ProjectIndex, call_name

_NARROW_INT = frozenset({"int8", "int16", "int32", "bool_", "bool"})
_INTISH = _NARROW_INT | {"int64"}
_DTYPE_TOKENS = _INTISH | {"float32", "float64"}

#: modules allowed to touch raw .zmap bounds (envelope impl + builders)
_ZMAP_OK = ("ops/scan.py", "storage/columnar.py",
            "docstore/pushdown.py")

def _dtype_tokens(text: str) -> Set[str]:
    return {t for t in _DTYPE_TOKENS if t in text}


def _is_sum_call(n: ast.Call) -> bool:
    # jnp.sum / jax.numpy.sum / *.segment_sum — NOT np.sum (numpy
    # already accumulates integers in platform int64)
    cn = call_name(n)
    if cn.endswith("segment_sum"):
        return True
    return (cn.split(".")[-1] == "sum"
            and (cn.startswith("jnp.") or cn.startswith("jax.")))


class NumericExactnessPass(AnalysisPass):
    id = "numeric_exactness"
    title = "exact-aggregate / zone-envelope numeric contract violation"
    hint = ("accumulate in int64 (dtype=jnp.int64, or quantize floats "
            "to int64 fixed-point); consume zone-map bounds through "
            "ops/scan.py's _f32_widen envelope; pass offset= to every "
            "compile_expr after the first")

    def run(self, index: ProjectIndex) -> List[Finding]:
        from ..callgraph import iter_defs
        out: List[Finding] = []
        for mod in index.modules():
            if mod.tree is None:
                continue
            # token gates: the rules only ever fire on source that
            # mentions these — skip the AST walks everywhere else
            if ".zmap" in mod.source and not mod.rel.endswith(_ZMAP_OK):
                for n in ast.walk(mod.tree):
                    if (isinstance(n, ast.Attribute)
                            and n.attr == "zmap"):
                        out.append(self.finding(
                            mod, n.lineno,
                            "raw zone-map bounds read outside the "
                            "f32-widen envelope — float32 block "
                            "min/max compared exactly can prune "
                            "blocks that contain matching rows",
                            detail="zone-envelope"))
            if not any(t in mod.source for t in
                       ("jnp.", "jax.", "segment_sum", "compile_expr")):
                continue
            for qual, _cls, node in iter_defs(mod.tree):
                self._check_def(mod, qual, node, out)
        return out

    def _check_def(self, mod, qual: str, node, out: List[Finding],
                   ) -> None:
        #: local name -> dtype tokens seen in its assignments
        evidence: Dict[str, Set[str]] = {}
        sums: List[ast.Call] = []
        compiles: List[ast.Call] = []

        def _own_nodes(root):
            """Source-order walk that stays out of nested defs —
            iter_defs hands those to their own _check_def, and the
            evidence map chains assignments in program order."""
            for n in ast.iter_child_nodes(root):
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                yield n
                yield from _own_nodes(n)

        for n in _own_nodes(node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                name = n.targets[0].id
                rhs = ast.unparse(n.value)
                toks = _dtype_tokens(rhs)
                # a cast chains its source's evidence: y =
                # x.astype(jnp.float32) keeps x's int taint on y
                for m in ast.walk(n.value):
                    if isinstance(m, ast.Name) and m.id in evidence:
                        toks |= evidence[m.id]
                if toks:
                    evidence.setdefault(name, set()).update(toks)
            elif isinstance(n, ast.Call):
                if _is_sum_call(n) and n.args:
                    sums.append(n)
                if call_name(n).split(".")[-1] == "compile_expr":
                    compiles.append(n)

        for n in sums:
            if any(kw.arg == "dtype" for kw in n.keywords):
                continue
            arg = n.args[0]
            text = ast.unparse(arg)
            toks = set(_dtype_tokens(text))
            for m in ast.walk(arg):
                if isinstance(m, ast.Name) and m.id in evidence:
                    toks |= evidence[m.id]
            narrow = toks & _NARROW_INT
            if narrow and "int64" not in toks and "float32" not in toks:
                out.append(self.finding(
                    mod, n.lineno,
                    f"sum over {'/'.join(sorted(narrow))}-evidenced "
                    f"value without dtype= — the accumulator "
                    "inherits the narrow dtype and overflows "
                    "(contract: exact int64)",
                    detail="sum-dtype"))
            elif "float32" in toks and toks & _INTISH:
                out.append(self.finding(
                    mod, n.lineno,
                    "int/bool value cast through float32 then "
                    "summed — exact counts become saturating f32 "
                    "adds above 2**24 (contract: exact int64, "
                    "quantize floats to fixed-point)",
                    detail="float-accumulator"))

        if len(compiles) >= 2:
            ordered = sorted(compiles, key=lambda c: (c.lineno,
                                                      c.col_offset))
            for c in ordered[1:]:
                if not any(kw.arg == "offset" for kw in c.keywords):
                    out.append(self.finding(
                        mod, c.lineno,
                        f"compile_expr call after the first in "
                        f"{qual} without offset= — it re-reads the "
                        "first expression's constant table (the "
                        "consts-offset regression)",
                        detail="consts-offset"))


PASS = NumericExactnessPass()
