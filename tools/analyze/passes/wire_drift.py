"""Pass: wire/codec drift — every field of a wire dataclass must
round-trip through its codec pair (and partial-result fields must be
combined).

The RPC layer ships dataclasses as msgpack dicts through hand-written
``*_to_wire`` / ``*_from_wire`` codec pairs.  Adding a field to the
dataclass without teaching BOTH codecs silently drops it after one
network hop — the request works in-process and in every single-node
test, then loses the field the first time it crosses the wire (the
shape of the ARRAY-const regression ``_expr_from_wire``'s docstring
documents).  The dataclass and its codecs live in different modules,
so only a cross-module check can keep them joined.

How it works, per REGISTRY entry (one per wire dataclass):

1. FIELDS — annotated assignments in the dataclass body (dataclass
   fields), from the class AST.
2. ENCODE — the encoder must read every field: an attribute access
   ``.field`` or the string literal ``"field"`` anywhere in the
   encoder's AST.  Missing -> finding at the encoder.
3. DECODE — the decoder must restore every field: a ``field=`` kwarg
   on a constructor call, positional constructor coverage (first N
   params), or the string literal.  Missing -> finding at the decoder.
4. IGNORE — fields that deliberately do NOT cross the wire carry a
   registry reason (e.g. ``server_assigned_read_ht`` is assigned by
   the SERVER after decode; serializing it would let a client forge a
   server-assigned read point).
5. COMBINED — partial-result fields (``agg_values``,
   ``group_counts``, ...) must ALSO appear in every registered
   combiner: a field that round-trips but is dropped when per-tablet
   partials merge is the same user-visible loss one hop later.

The registry is explicit on purpose — a new wire dataclass means a new
entry (tests pin the known ones).  Suppress at the reported line:
``# analysis-ok(wire_drift): <reason>``.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import AnalysisPass, Finding, ProjectIndex

_DOCDB = "yugabyte_db_tpu/docdb"
_OPS = "yugabyte_db_tpu/ops"
_MV = "yugabyte_db_tpu/matview"

REGISTRY: Tuple[dict, ...] = (
    {
        "dataclass": (f"{_DOCDB}/operations.py", "ReadRequest"),
        "encode": (f"{_DOCDB}/wire.py", "read_request_to_wire"),
        "decode": (f"{_DOCDB}/wire.py", "read_request_from_wire"),
        "ignore": {
            "server_assigned_read_ht":
                "server-local: set by the serving tablet AFTER decode "
                "and consumed in-process; shipping it would let a "
                "client forge a server-assigned (restartable) read "
                "point",
        },
        "combined": {},
    },
    {
        "dataclass": (f"{_DOCDB}/operations.py", "ReadResponse"),
        "encode": (f"{_DOCDB}/wire.py", "read_response_to_wire"),
        "decode": (f"{_DOCDB}/wire.py", "read_response_from_wire"),
        "ignore": {},
        # the client fan-out combine is the one place ReadResponse
        # partials are unpacked by FIELD NAME into the shared
        # combiners (scan.combine_*_partials take positional tuples)
        "combined": {
            "agg_values": [("yugabyte_db_tpu/client/client.py",
                            "YBClient._combine")],
            "group_counts": [("yugabyte_db_tpu/client/client.py",
                              "YBClient._combine")],
            "group_values": [("yugabyte_db_tpu/client/client.py",
                              "YBClient._combine")],
        },
    },
    {
        "dataclass": (f"{_DOCDB}/operations.py", "WriteRequest"),
        "encode": (f"{_DOCDB}/wire.py", "write_request_to_wire"),
        "decode": (f"{_DOCDB}/wire.py", "write_request_from_wire"),
        "ignore": {},
        "combined": {},
    },
    {
        "dataclass": (f"{_DOCDB}/operations.py", "RowOp"),
        "encode": (f"{_DOCDB}/wire.py", "write_request_to_wire"),
        "decode": (f"{_DOCDB}/wire.py", "write_request_from_wire"),
        "ignore": {},
        "combined": {},
    },
    {
        "dataclass": (f"{_OPS}/join_scan.py", "JoinWire"),
        "encode": (f"{_DOCDB}/wire.py", "_join_to_wire"),
        "decode": (f"{_DOCDB}/wire.py", "_join_from_wire"),
        "ignore": {},
        "combined": {},
    },
    {
        "dataclass": (f"{_OPS}/scan.py", "HashGroupSpec"),
        "encode": (f"{_DOCDB}/wire.py", "read_request_to_wire"),
        "decode": (f"{_DOCDB}/wire.py", "read_request_from_wire"),
        "ignore": {},
        "combined": {},
    },
    {
        "dataclass": (f"{_OPS}/grouped_scan.py", "DictGroupSpec"),
        "encode": (f"{_DOCDB}/wire.py", "read_request_to_wire"),
        "decode": (f"{_DOCDB}/wire.py", "read_request_from_wire"),
        "ignore": {},
        "combined": {},
    },
    {
        "dataclass": (f"{_MV}/definition.py", "ViewDef"),
        "encode": (f"{_MV}/definition.py", "ViewDef.to_wire"),
        "decode": (f"{_MV}/definition.py", "viewdef_from_wire"),
        "ignore": {},
        "combined": {},
    },
)


class WireDriftPass(AnalysisPass):
    id = "wire_drift"
    title = "wire dataclass field not round-tripped by its codec pair"
    hint = ("serialize the field in *_to_wire AND restore it in "
            "*_from_wire (and add it to the partial combiners if it "
            "carries results); if it deliberately stays server-local, "
            "record an ignore reason in the wire_drift registry")

    def __init__(self, registry: Optional[Sequence[dict]] = None):
        #: overridable so fixture tests can run synthetic registries
        self.registry: Tuple[dict, ...] = tuple(
            REGISTRY if registry is None else registry)

    def run(self, index: ProjectIndex) -> List[Finding]:
        out: List[Finding] = []
        for ent in self.registry:
            drel, dcls = ent["dataclass"]
            cls_node = _find_class(index, drel, dcls)
            dmod = index.module(drel)
            if cls_node is None or dmod is None:
                anchor = dmod or index.modules()[0]
                out.append(self.finding(
                    anchor, 1,
                    f"stale wire_drift registry entry: class {dcls!r} "
                    f"not found in {drel}",
                    detail=f"{drel}::{dcls}"))
                continue
            fields = _dataclass_fields(cls_node)
            if not fields:
                continue

            enc_rel, enc_qual = ent["encode"]
            dec_rel, dec_qual = ent["decode"]
            enc = _find_def(index, enc_rel, enc_qual)
            dec = _find_def(index, dec_rel, dec_qual)
            for side, node, rel, qual in (("encoder", enc, enc_rel,
                                           enc_qual),
                                          ("decoder", dec, dec_rel,
                                           dec_qual)):
                if node is None:
                    anchor = index.module(rel) or dmod
                    out.append(self.finding(
                        anchor, 1,
                        f"stale wire_drift registry entry: {side} "
                        f"{qual!r} not found in {rel}",
                        detail=f"{rel}::{qual}"))
            if enc is None or dec is None:
                continue

            enc_mod = index.module(enc_rel)
            dec_mod = index.module(dec_rel)
            enc_cover = _mentions(enc)
            dec_cover = _mentions(dec) | _positional_cover(dec, dcls,
                                                           fields)
            for i, f in enumerate(fields):
                if f in ent["ignore"]:
                    continue
                if f not in enc_cover:
                    out.append(self.finding(
                        enc_mod, enc.lineno,
                        f"{dcls}.{f} is never serialized by "
                        f"{enc_qual} — the field silently drops on "
                        "the first network hop",
                        detail=f"{dcls}.{f}:encode"))
                if f not in dec_cover:
                    out.append(self.finding(
                        dec_mod, dec.lineno,
                        f"{dcls}.{f} is never restored by "
                        f"{dec_qual} — the field silently resets to "
                        "its default after one network hop",
                        detail=f"{dcls}.{f}:decode"))

            for f, combiners in ent["combined"].items():
                for crel, cqual in combiners:
                    cnode = _find_def(index, crel, cqual)
                    cmod = index.module(crel)
                    if cnode is None or cmod is None:
                        anchor = index.module(crel) or dmod
                        out.append(self.finding(
                            anchor, 1,
                            f"stale wire_drift registry entry: "
                            f"combiner {cqual!r} not found in {crel}",
                            detail=f"{crel}::{cqual}"))
                        continue
                    if f not in _mentions(cnode):
                        out.append(self.finding(
                            cmod, cnode.lineno,
                            f"{dcls}.{f} round-trips the wire but "
                            f"{cqual} never combines it — the field "
                            "is lost when per-tablet partials merge",
                            detail=f"{dcls}.{f}:combine"))
        return out


# --- AST lookups ----------------------------------------------------------
def _find_class(index: ProjectIndex, rel: str,
                name: str) -> Optional[ast.ClassDef]:
    mod = index.module(rel)
    if mod is None or mod.tree is None:
        return None
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.ClassDef) and n.name == name:
            return n
    return None


def _find_def(index: ProjectIndex, rel: str, qual: str):
    mod = index.module(rel)
    if mod is None or mod.tree is None:
        return None
    from ..callgraph import iter_defs
    for q, _cls, node in iter_defs(mod.tree):
        if q == qual:
            return node
    return None


def _dataclass_fields(cls: ast.ClassDef) -> List[str]:
    """Annotated-assignment names in declaration order (the dataclass
    __init__ parameter order)."""
    out: List[str] = []
    for s in cls.body:
        if isinstance(s, ast.AnnAssign) and isinstance(s.target,
                                                       ast.Name):
            out.append(s.target.id)
    return out


def _mentions(node: ast.AST) -> Set[str]:
    """Names a def plausibly touches as fields: attribute accesses,
    keyword arguments, and string literals."""
    got: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute):
            got.add(n.attr)
        elif isinstance(n, ast.keyword) and n.arg:
            got.add(n.arg)
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            got.add(n.value)
    return got


def _positional_cover(node: ast.AST, cls_name: str,
                      fields: List[str]) -> Set[str]:
    """Fields covered positionally: ``Cls(a, b, key=...)`` covers the
    first two declared fields."""
    got: Set[str] = set()
    for n in ast.walk(node):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == cls_name):
            npos = sum(1 for a in n.args
                       if not isinstance(a, ast.Starred))
            got |= set(fields[:npos])
    return got


PASS = WireDriftPass()
