"""Pass: ASH wait-state discipline — no free-text drift.

The whole value of wait-state attribution (``cluster_p99_attribution``,
the rpc_tracez histograms) rests on wait states being a CLOSED
vocabulary: the bench's category mapping, the collector's dominant-wait
logic and every dashboard keys on exact strings.  One typo'd
``wait_status("WalFsync")`` site would silently vanish from every
histogram while looking instrumented.

Contract enforced tree-wide:

1. Every ``wait_status(...)`` call's state argument must be a STRING
   LITERAL — a variable/attribute/f-string cannot be checked against
   the table and is flagged (suppressible where a computed state is
   genuinely needed).
2. Every literal must appear in the canonical ``WAIT_STATES`` table
   (the frozenset assigned in ``yugabyte_db_tpu/utils/trace.py`` —
   discovered from the AST, so the pass tracks the table as it grows
   with zero pass edits).

Known lexical limits: the table is discovered as the first module-level
``WAIT_STATES = frozenset({...})`` / set-literal assignment in the
indexed tree (fixtures define their own mini table); indirect calls
through aliases other than ``*wait_status`` are invisible.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import AnalysisPass, Finding, ProjectIndex, call_name


def _literal_states(value: ast.expr) -> Optional[Set[str]]:
    """String members of a frozenset({...}) / set / tuple literal."""
    if isinstance(value, ast.Call) and call_name(value) == "frozenset" \
            and value.args:
        value = value.args[0]
    if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
        out: Set[str] = set()
        for el in value.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            out.add(el.value)
        return out
    return None


def find_state_table(index: ProjectIndex):
    """(module, states) of the canonical WAIT_STATES table, preferring
    the real utils/trace.py over any other definition."""
    best = None
    for mi in index.modules():
        if mi.tree is None:
            continue
        for node in mi.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and t.id == "WAIT_STATES"
                       for t in node.targets):
                continue
            states = _literal_states(node.value)
            if states is None:
                continue
            if mi.rel.replace("\\", "/").endswith("utils/trace.py"):
                return mi, states
            if best is None:
                best = (mi, states)
    return best if best is not None else (None, None)


class TraceDisciplinePass(AnalysisPass):
    id = "trace_discipline"
    title = "ASH wait-state discipline (canonical WAIT_STATES only)"
    hint = ("wait_status() states are a closed vocabulary: add the "
            "state to trace.WAIT_STATES (and the collector's category "
            "map) instead of inventing a string at the call site")

    def run(self, index: ProjectIndex) -> List[Finding]:
        table_mod, states = find_state_table(index)
        if not states:
            return []     # no table in this tree (bare fixture)
        out: List[Finding] = []
        for mi in index.modules():
            if mi.tree is None or mi is table_mod:
                continue
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if not (name == "wait_status"
                        or name.endswith(".wait_status")):
                    continue
                if not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    if arg.value not in states:
                        out.append(self.finding(
                            mi, node.lineno,
                            f"wait_status({arg.value!r}) is not in the "
                            "canonical trace.WAIT_STATES table — "
                            "free-text wait states vanish from every "
                            "ASH histogram and attribution map",
                            detail=arg.value))
                else:
                    out.append(self.finding(
                        mi, node.lineno,
                        "wait_status() state is not a string literal — "
                        "the canonical-table check cannot see a "
                        "computed state",
                        detail="non-literal"))
        return out


PASS = TraceDisciplinePass()
