"""Pass: blocking calls inside ``async def`` bodies — direct AND
reached transitively through sync helpers.

The scheduler (yugabyte_db_tpu/sched/) multiplexes every lane's
dispatch over one event loop, so a synchronous stall inside an async
handler no longer slows one RPC — it freezes admission, batching
windows, Raft heartbeats and lease renewal for the whole server.

Two layers:

1. LEXICAL (the original pass): a blocking dotted call written
   directly in an async def body.  Nested sync ``def`` bodies are NOT
   flagged — they are frequently executor targets; nested async defs
   get their own scan.
2. TRANSITIVE (call-graph powered): a call from an async def that
   resolves to a *sync* project def whose bounded-depth summary
   contains a blocking call — the ``async def handler():
   self._cleanup()`` / ``def _cleanup(): shutil.rmtree(...)`` shape
   the lexical layer was blind to.  The finding lands on the call line
   in the async def and reports the full helper chain.  Propagation
   follows only SYNC callees (an awaited async callee is scanned on
   its own), and a blocking call already suppressed at its own line
   (``analysis-ok(async_blocking)`` / ``blocking-ok``) is an
   acknowledged bounded stall — it does not taint its callers.

Transitive propagation uses the STRONG blocker set (sleeps, fsync,
subprocess, socket resolvers, tree copies/removals, cross-FS renames).
Bare ``open``/``io.open`` stay lexical-only: one helper opening a tiny
metadata file is the repo's accepted idiom (13 annotated sites), and
propagating it would make every config-reading helper taint every
caller — the signal drowns.  ANALYSIS.md documents the split.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..core import (AnalysisPass, Finding, ModuleInfo, ProjectIndex,
                    call_name, is_suppressed)

#: dotted call names that stall the loop.  Name-based on purpose: the
#: analyzer never imports the code it checks.  `open` covers the sync
#: read/write family (a handle opened on the loop gets read on the
#: loop); socket module resolvers/connects block on the network.
BLOCKING = {
    "time.sleep",
    "open", "io.open",
    "os.fsync", "os.fdatasync", "os.sync",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
    "socket.create_connection", "socket.getaddrinfo",
    "socket.gethostbyname", "socket.gethostbyaddr",
    "shutil.copyfile", "shutil.copytree", "shutil.rmtree",
    "os.replace", "os.rename",
}

#: the subset that taints callers transitively — unbounded or
#: device/network stalls.  `open`/`io.open` are deliberately absent
#: (see module docstring).
TRANSITIVE_BLOCKING = BLOCKING - {"open", "io.open"}

_HINTS = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "open": "wrap in `run_in_executor` for anything non-trivial",
    "io.open": "wrap in `run_in_executor` for anything non-trivial",
    "os.fsync": "fsync is a device stall; move it to an executor",
    "os.fdatasync": "fdatasync is a device stall; move it to an executor",
}
_DEFAULT_HINT = ("move the call into `run_in_executor`, or annotate "
                 "`# analysis-ok(async_blocking): <reason>` if the stall "
                 "is genuinely bounded")


def render_chain(graph, start_text: str, hops, hazard: str) -> str:
    """``helper() -> _cleanup (storage/lsm.py:93) -> shutil.rmtree``:
    the witness path from the async-side call down to the direct
    blocking call."""
    parts = [f"{start_text}()"]
    for i in range(1, len(hops)):
        # hop i is named at the line in hop i-1 that calls it
        parts.append(f"{hops[i][1]} ({hops[i - 1][0]}:{hops[i - 1][2]})")
    last = hops[-1] if hops else None
    tail = f"{hazard} ({last[0]}:{last[2]})" if last else hazard
    parts.append(tail)
    return " -> ".join(parts)


class AsyncBlockingPass(AnalysisPass):
    id = "async_blocking"
    title = "blocking call inside async def"
    hint = _DEFAULT_HINT

    def run(self, index: ProjectIndex) -> List[Finding]:
        out: List[Finding] = []
        for mod in index.modules():
            if mod.tree is not None:
                self.scan_module(mod, out)
        self._scan_transitive(index, out)
        return out

    # --- layer 1: lexical -------------------------------------------------
    def scan_module(self, mod: ModuleInfo, out: List[Finding]) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                for stmt in node.body:
                    self._scan(mod, stmt, out)

    def _scan(self, mod: ModuleInfo, node: ast.AST,
              out: List[Finding]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.Lambda)):
            return                      # executor-target territory
        if isinstance(node, ast.AsyncFunctionDef):
            return                      # scanned by its own walk visit
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in BLOCKING:
                out.append(self.finding(
                    mod, node.lineno,
                    f"blocking call `{name}` inside async def",
                    detail=name,
                    hint=_HINTS.get(name, _DEFAULT_HINT)))
        for child in ast.iter_child_nodes(node):
            self._scan(mod, child, out)

    # --- layer 2: transitive (call graph) ---------------------------------
    def _scan_transitive(self, index: ProjectIndex,
                         out: List[Finding]) -> None:
        graph = index.call_graph()

        def direct(key: str) -> Dict[str, int]:
            d = graph.def_fact(key)
            if d is None:
                return {}
            rel, _ = graph.split(key)
            mod = index.module(rel)
            hits: Dict[str, int] = {}
            for line, text in d["calls"]:
                if text in TRANSITIVE_BLOCKING and text not in hits \
                        and mod is not None \
                        and not is_suppressed(mod, line, self.id):
                    hits[text] = line
            return hits

        def follow(key: str) -> bool:
            return not graph.is_async(key)

        def edge_ok(key: str, line: int) -> bool:
            # an analysis-ok(async_blocking) annotation on an
            # INTERMEDIATE sync call (a flag-gated legacy path, a
            # deliberate bounded drain) stops taint at that call site
            # without silencing the callee for its other callers
            rel, _ = graph.split(key)
            m = index.module(rel)
            return m is None or not is_suppressed(m, line, self.id)

        seen: Set[tuple] = set()
        for key, d in graph.defs():
            if not d["async"]:
                continue
            rel, qual = graph.split(key)
            mod = index.module(rel)
            if mod is None:
                continue
            for line, text, tgt in graph.edges(key):
                if tgt is None or graph.is_async(tgt):
                    continue
                # NB: no edge_ok here — an annotated async-side call
                # still EMITS its finding so the runner counts it as
                # suppressed (the baseline gate's accounting); edge_ok
                # only gates intermediate hops inside the summaries
                summ = graph.summarize(tgt, self.id, direct, follow,
                                       edge_ok)
                for bname in sorted(summ):
                    sig = (rel, line, bname)
                    if sig in seen:
                        continue
                    seen.add(sig)
                    hops = graph.chain(tgt, bname, self.id, direct,
                                       follow, edge_ok)
                    out.append(self.finding(
                        mod, line,
                        f"blocking call `{bname}` reached from async "
                        f"def `{d['name']}` via sync call chain: "
                        f"{render_chain(graph, text, hops, bname)}",
                        detail=bname,
                        hint=_HINTS.get(bname, _DEFAULT_HINT)))
        return


PASS = AsyncBlockingPass()
