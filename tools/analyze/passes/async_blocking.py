"""Pass: blocking calls lexically inside ``async def`` bodies.

The scheduler (yugabyte_db_tpu/sched/) multiplexes every lane's
dispatch over one event loop, so a synchronous stall inside an async
handler no longer slows one RPC — it freezes admission, batching
windows, Raft heartbeats and lease renewal for the whole server.

Generalizes the original tools/check_blocking.py pass (tserver/ + rpc/
only; time.sleep / open / os.fsync) to the whole tree with a wider
offender set.  Nested sync ``def`` bodies are NOT flagged — they are
frequently executor targets; nested async defs get their own scan.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import AnalysisPass, Finding, ModuleInfo, ProjectIndex, call_name

#: dotted call names that stall the loop.  Name-based on purpose: the
#: analyzer never imports the code it checks.  `open` covers the sync
#: read/write family (a handle opened on the loop gets read on the
#: loop); socket module resolvers/connects block on the network.
BLOCKING = {
    "time.sleep",
    "open", "io.open",
    "os.fsync", "os.fdatasync", "os.sync",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
    "socket.create_connection", "socket.getaddrinfo",
    "socket.gethostbyname", "socket.gethostbyaddr",
    "shutil.copyfile", "shutil.copytree", "shutil.rmtree",
    "os.replace", "os.rename",
}

_HINTS = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "open": "wrap in `run_in_executor` for anything non-trivial",
    "io.open": "wrap in `run_in_executor` for anything non-trivial",
    "os.fsync": "fsync is a device stall; move it to an executor",
    "os.fdatasync": "fdatasync is a device stall; move it to an executor",
}
_DEFAULT_HINT = ("move the call into `run_in_executor`, or annotate "
                 "`# analysis-ok(async_blocking): <reason>` if the stall "
                 "is genuinely bounded")


class AsyncBlockingPass(AnalysisPass):
    id = "async_blocking"
    title = "blocking call inside async def"
    hint = _DEFAULT_HINT

    def run(self, index: ProjectIndex) -> List[Finding]:
        out: List[Finding] = []
        for mod in index.modules():
            if mod.tree is not None:
                self.scan_module(mod, out)
        return out

    def scan_module(self, mod: ModuleInfo, out: List[Finding]) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                for stmt in node.body:
                    self._scan(mod, stmt, out)

    def _scan(self, mod: ModuleInfo, node: ast.AST,
              out: List[Finding]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.Lambda)):
            return                      # executor-target territory
        if isinstance(node, ast.AsyncFunctionDef):
            return                      # scanned by its own walk visit
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in BLOCKING:
                out.append(self.finding(
                    mod, node.lineno,
                    f"blocking call `{name}` inside async def",
                    detail=name,
                    hint=_HINTS.get(name, _DEFAULT_HINT)))
        for child in ast.iter_child_nodes(node):
            self._scan(mod, child, out)


PASS = AsyncBlockingPass()
