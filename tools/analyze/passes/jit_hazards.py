"""Pass: host-sync / shape hazards inside jitted kernel bodies.

The Q1/Q6 and compaction north-star numbers depend on the compile-once
property: every kernel compiles once per pow2 shape bucket and never
syncs the host mid-trace.  Three mechanical ways the tree can lose it:

1. HOST SYNC inside a jit body — ``.item()`` / ``.tolist()`` /
   ``.block_until_ready()`` on a traced value, ``float()``/``int()`` of
   a traced value, ``np.asarray`` of a traced value.  Under tracing
   these either error late or silently fall back to op-by-op dispatch.
2. PYTHON CONTROL FLOW on a traced value — ``if``/``while``/``assert``
   on a tensor makes the trace shape- or value-dependent (a recompile
   per branch at best, TracerBoolConversionError at worst).
3. LITERAL SHAPES at jitted call sites — building a literal-shaped
   array directly in the call (``kernel(jnp.zeros(50000), ...)``)
   bypasses the pow2-bucket helpers, so every input size is a fresh
   compile.

Taintedness is tracked intraprocedurally: non-static parameters are
traced; ``.shape`` / ``.ndim`` / ``.dtype`` / ``.size`` and ``len()``
of a traced value are Python-static and UNTAINT, which is exactly the
idiom the repo's kernels use for bucket math.

Jitted functions are found by decorator (``@jax.jit``,
``@partial(jax.jit, static_argnames=...)``) and by assignment
(``fn = jax.jit(raw)`` where ``raw`` is a module-local def).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import AnalysisPass, Finding, ModuleInfo, ProjectIndex, call_name

_UNTAINT_ATTRS = {"shape", "ndim", "dtype", "size"}
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_HOST_CAST_FUNCS = {"float", "int", "bool", "complex"}
_HOST_ASARRAY = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
                 "onp.asarray", "onp.array"}
_ARRAY_CTORS = {f"{m}.{f}" for m in ("np", "jnp", "numpy", "jax.numpy")
                for f in ("zeros", "ones", "empty", "full", "arange")}


def _jit_target(dec: ast.expr) -> Optional[Tuple[bool, ast.expr]]:
    """Is this decorator / call expression a jax.jit wrapper?  Returns
    (True, call_node_or_None) when it is."""
    if isinstance(dec, (ast.Attribute, ast.Name)):
        name = ast.unparse(dec)
        if name in ("jax.jit", "jit"):
            return True, None
    if isinstance(dec, ast.Call):
        fname = call_name(dec)
        if fname in ("jax.jit", "jit"):
            return True, dec
        if fname in ("partial", "functools.partial") and dec.args:
            inner = dec.args[0]
            if isinstance(inner, (ast.Attribute, ast.Name)) \
                    and ast.unparse(inner) in ("jax.jit", "jit"):
                return True, dec
    return None


def _static_names(call: Optional[ast.Call],
                  fn: Optional[ast.FunctionDef]) -> Set[str]:
    """static_argnames / static_argnums out of a jit/partial call."""
    out: Set[str] = set()
    if call is None:
        return out
    params: List[str] = []
    if fn is not None:
        a = fn.args
        params = [x.arg for x in a.posonlyargs + a.args]
    for kw in call.keywords:
        v = kw.value
        if kw.arg == "static_argnames":
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                out.update(e.value for e in v.elts
                           if isinstance(e, ast.Constant)
                           and isinstance(e.value, str))
        elif kw.arg == "static_argnums":
            nums = []
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums = [e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)]
            out.update(params[i] for i in nums if 0 <= i < len(params))
        elif kw.arg is not None and fn is not None:
            # partial(jax.jit, ...) can't bind kernel params, but
            # partial(kernel, x=...) style never reaches here
            pass
    return out


class JitHazardsPass(AnalysisPass):
    id = "jit_hazards"
    title = "host-sync / shape hazard inside a jitted kernel"
    hint = ("keep jit bodies pure-traced: jnp.where over Python if, "
            "static_argnames for real constants, pow2 bucket helpers "
            "for shapes")

    def run(self, index: ProjectIndex) -> List[Finding]:
        out: List[Finding] = []
        for mod in index.modules():
            if mod.tree is not None:
                self._scan_module(mod, out)
        return out

    # --- per-module -------------------------------------------------------
    def _scan_module(self, mod: ModuleInfo, out: List[Finding]) -> None:
        defs: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef):
                defs.setdefault(node.name, node)

        jitted: Dict[str, Tuple[ast.FunctionDef, Set[str]]] = {}
        jitted_names: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    hit = _jit_target(dec)
                    if hit:
                        statics = _static_names(hit[1], node)
                        jitted[node.name] = (node, statics)
                        jitted_names.add(node.name)
            elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                             ast.Call):
                hit = _jit_target(node.value)
                if hit and hit[1] is not None and hit[1].args:
                    wrapped = hit[1].args[0]
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            jitted_names.add(tgt.id)
                    if isinstance(wrapped, ast.Name) \
                            and wrapped.id in defs:
                        fn = defs[wrapped.id]
                        jitted[fn.name] = (fn,
                                           _static_names(hit[1], fn))

        for fn, statics in jitted.values():
            self._scan_jit_fn(mod, fn, statics, out)
        # jitted_names holds the actual jitted callables: decorated def
        # names + jit()-assignment targets.  A raw def wrapped by
        # assignment is deliberately NOT included — calling it directly
        # runs eagerly (no compile, no trap).
        self._scan_call_sites(mod, jitted_names, out)

    # --- call-site literal-shape check ------------------------------------
    def _scan_call_sites(self, mod: ModuleInfo, jitted_names: Set[str],
                         out: List[Finding]) -> None:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            # bare-Name calls only: jitted names are module-local, and
            # leaf-matching attribute calls would flag any method that
            # happens to share a jitted fn's name
            if not (isinstance(node.func, ast.Name)
                    and node.func.id in jitted_names):
                continue
            fname = node.func.id
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Call) \
                        and call_name(arg) in _ARRAY_CTORS and arg.args \
                        and _is_literal_shape(arg.args[0]):
                    out.append(self.finding(
                        mod, arg.lineno,
                        f"literal-shaped `{call_name(arg)}` built at the "
                        f"call site of jitted `{fname}` — bypasses the "
                        f"pow2 bucket helpers, so each input size is a "
                        f"fresh compile",
                        detail=f"{fname}:{call_name(arg)}",
                        hint="round the shape through the module's pow2 "
                             "bucket helper before the kernel call"))

    # --- jit-body taint walk ----------------------------------------------
    def _scan_jit_fn(self, mod: ModuleInfo, fn: ast.FunctionDef,
                     statics: Set[str], out: List[Finding]) -> None:
        a = fn.args
        params = [x.arg for x in a.posonlyargs + a.args + a.kwonlyargs]
        taint = {p for p in params if p not in statics}
        self._scan_block(mod, fn.body, taint, fn.name, out)

    def _scan_block(self, mod: ModuleInfo, stmts, taint: Set[str],
                    where: str, out: List[Finding]) -> None:
        for s in stmts:
            self._scan_stmt(mod, s, taint, where, out)

    def _scan_stmt(self, mod: ModuleInfo, s: ast.stmt, taint: Set[str],
                   where: str, out: List[Finding]) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested body sees outer traced names as closures; its own
            # params shadow (we cannot know if they are traced)
            inner = set(taint)
            ia = s.args
            for x in ia.posonlyargs + ia.args + ia.kwonlyargs:
                inner.discard(x.arg)
            self._scan_block(mod, s.body, inner, where, out)
            return
        if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = s.value
            t = self._expr(mod, value, taint, where, out) \
                if value is not None else False
            targets = s.targets if isinstance(s, ast.Assign) else [s.target]
            if isinstance(s, ast.AugAssign):
                t = t or self._names_tainted(s.target, taint)
            for tgt in targets:
                self._mark(tgt, taint, t)
            return
        if isinstance(s, ast.For):
            it = self._expr(mod, s.iter, taint, where, out)
            if it:
                out.append(self.finding(
                    mod, s.lineno,
                    f"Python `for` over a traced value in jitted "
                    f"`{where}` — unrolls per element (or errors); use "
                    f"lax.scan/fori_loop",
                    detail=f"{where}:for"))
            self._mark(s.target, taint, it)
            self._scan_block(mod, s.body, taint, where, out)
            self._scan_block(mod, s.orelse, taint, where, out)
            return
        if isinstance(s, (ast.If, ast.While)):
            kind = "if" if isinstance(s, ast.If) else "while"
            if self._expr(mod, s.test, taint, where, out):
                out.append(self.finding(
                    mod, s.lineno,
                    f"Python `{kind}` on a traced value in jitted "
                    f"`{where}` — shape/value-dependent trace (use "
                    f"jnp.where / lax.cond)",
                    detail=f"{where}:{kind}"))
            self._scan_block(mod, s.body, taint, where, out)
            self._scan_block(mod, s.orelse, taint, where, out)
            return
        if isinstance(s, ast.Assert):
            if self._expr(mod, s.test, taint, where, out):
                out.append(self.finding(
                    mod, s.lineno,
                    f"assert on a traced value in jitted `{where}` — "
                    f"bool() of a tracer",
                    detail=f"{where}:assert"))
            return
        # generic statements: scan child expressions / blocks
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._expr(mod, child, taint, where, out)
            elif isinstance(child, ast.stmt):
                self._scan_stmt(mod, child, taint, where, out)
            elif isinstance(child, (ast.excepthandler, ast.withitem)):
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.expr):
                        self._expr(mod, sub, taint, where, out)
                    elif isinstance(sub, ast.stmt):
                        self._scan_stmt(mod, sub, taint, where, out)

    @staticmethod
    def _mark(target: ast.expr, taint: Set[str], tainted: bool) -> None:
        if isinstance(target, ast.Name):
            (taint.add if tainted else taint.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                JitHazardsPass._mark(e, taint, tainted)
        elif isinstance(target, ast.Starred):
            JitHazardsPass._mark(target.value, taint, tainted)

    @staticmethod
    def _names_tainted(e: ast.expr, taint: Set[str]) -> bool:
        return any(isinstance(n, ast.Name) and n.id in taint
                   for n in ast.walk(e))

    def _expr(self, mod: ModuleInfo, e: ast.expr, taint: Set[str],
              where: str, out: List[Finding]) -> bool:
        """Scan an expression for hazards; returns its taintedness."""
        if isinstance(e, ast.Name):
            return e.id in taint
        if isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Attribute):
            base = self._expr(mod, e.value, taint, where, out)
            if e.attr in _UNTAINT_ATTRS:
                return False            # Python-static under tracing
            return base
        if isinstance(e, ast.Lambda):
            return False                # opaque; sort keys etc.
        if isinstance(e, ast.IfExp):
            if self._expr(mod, e.test, taint, where, out):
                out.append(self.finding(
                    mod, e.lineno,
                    f"conditional expression on a traced value in jitted "
                    f"`{where}` — use jnp.where",
                    detail=f"{where}:ifexp"))
            t1 = self._expr(mod, e.body, taint, where, out)
            t2 = self._expr(mod, e.orelse, taint, where, out)
            return t1 or t2
        if isinstance(e, ast.Call):
            fname = call_name(e)
            func_taint = False
            if isinstance(e.func, ast.Attribute):
                func_taint = self._expr(mod, e.func.value, taint,
                                        where, out)
                if e.func.attr in _HOST_SYNC_METHODS and func_taint:
                    out.append(self.finding(
                        mod, e.lineno,
                        f"host sync `.{e.func.attr}()` on a traced value "
                        f"in jitted `{where}`",
                        detail=f"{where}:{e.func.attr}"))
            arg_taints = [self._expr(mod, a, taint, where, out)
                          for a in e.args]
            kw_taints = [self._expr(mod, k.value, taint, where, out)
                         for k in e.keywords]
            any_arg = any(arg_taints) or any(kw_taints)
            if fname in _HOST_CAST_FUNCS and any_arg:
                out.append(self.finding(
                    mod, e.lineno,
                    f"`{fname}()` of a traced value in jitted `{where}` "
                    f"— forces a host sync / concretization",
                    detail=f"{where}:{fname}"))
            if fname in _HOST_ASARRAY and any_arg:
                out.append(self.finding(
                    mod, e.lineno,
                    f"`{fname}` of a traced value in jitted `{where}` — "
                    f"pulls the value to host numpy mid-trace",
                    detail=f"{where}:{fname}"))
            if fname == "len":
                return False            # len of traced is static
            return func_taint or any_arg
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.DictComp,
                          ast.GeneratorExp)):
            # approximate: tainted if any comprehension input is
            return self._names_tainted(e, taint)
        # BinOp/UnaryOp/Compare/BoolOp/Subscript/Tuple/... : any child
        t = False
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                t = self._expr(mod, child, taint, where, out) or t
            elif isinstance(child, ast.comprehension):
                t = self._names_tainted(child, taint) or t
        return t


def _is_literal_shape(e: ast.expr) -> bool:
    if isinstance(e, ast.Constant) and isinstance(e.value, int):
        return True
    if isinstance(e, (ast.Tuple, ast.List)):
        return all(isinstance(x, ast.Constant) and isinstance(x.value, int)
                   for x in e.elts) and bool(e.elts)
    return False


PASS = JitHazardsPass()
