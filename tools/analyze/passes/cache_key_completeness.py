"""Pass: cache-key completeness — every runtime input a keyed
computation reads must be represented in its cache key.

The device caches (DeviceBlockCache batches, kernel-signature memo
dicts) key compiled/built artifacts by structural signature.  A runtime
input that AFFECTS the cached value but is MISSING from the key makes a
warm cache serve a stale artifact after the input changes — the PR-9
regression class (``device_float_dtype`` changed, batch cache kept
serving float32 batches).  The read of the input and the construction
of the key live in different functions, so only an interprocedural
check can pair them.

How it works, per REGISTRY entry (one entry per key constructor):

1. KEY TEXT — the key constructor's key-building source: for a def
   whose name mentions ``key``/``sig`` the whole def; otherwise the
   key argument of every ``*cache*.<method>(...)`` call plus the
   right-hand side of every assignment to a ``*key*``/``*sig*`` name.
   ``key_helpers`` (dedicated key-constructor defs whose result is
   embedded, e.g. ``_batch_cache_key`` under the chunk keys) extend
   the key text.
2. FLAG CLOSURE — every ``flags.get("<literal>")`` transitively
   reachable from the entry's ``roots`` (the defs that COMPUTE the
   cached value) via the call graph.  Each reached flag must appear as
   a literal in the key text or carry an ``allow`` reason in the
   registry (e.g. "captured via prune_sig") — else a finding at the
   key constructor, with the witness call chain to the read.
3. MUST-MENTION — structural key components that are easy to drop in
   a refactor (``prune_sig``, ``dict_sig``, ``chunk_rows``, ...) are
   pinned as registry substrings; key text losing one is a finding.
4. STALENESS — a registry entry whose def no longer exists is itself
   a finding, so the registry cannot rot silently.

The registry is intentionally explicit: adding a new keyed cache means
adding an entry here (tests enforce the known constructors stay
registered).  Suppress at the key constructor's def line:
``# analysis-ok(cache_key_completeness): <reason>``.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import AnalysisPass, Finding, ProjectIndex, call_name

_OPS = "yugabyte_db_tpu/ops"
_DOCDB = "yugabyte_db_tpu/docdb"

#: one entry per key constructor; see the module docstring for fields
REGISTRY: Tuple[dict, ...] = (
    {
        "key_builder": (f"{_DOCDB}/operations.py",
                        "DocReadOperation._batch_cache_key"),
        "roots": [(f"{_OPS}/device_batch.py", "build_batch")],
        "key_helpers": [],
        "allow": {},
        "must_mention": [
            ("write_generation", "batch must rebuild after writes"),
            ("device_float_dtype", "PR-9 regression: runtime dtype "
                                   "switch must re-key the batch"),
        ],
    },
    {
        "key_builder": (f"{_OPS}/stream_scan.py",
                        "streaming_scan_aggregate.build"),
        "roots": [(f"{_OPS}/stream_scan.py",
                   "streaming_scan_aggregate.build")],
        "key_helpers": [(f"{_DOCDB}/operations.py",
                         "DocReadOperation._batch_cache_key")],
        "allow": {},
        "must_mention": [
            ("cache_key", "caller prefix (carries the batch key)"),
            ("chunk_rows", "runtime streaming_chunk_rows re-plan"),
            ("bucket", "pow2 pad bucket is part of batch shape"),
            ("prune_sig", "zone-pruned chunk list identity"),
            ("dict_sig", "dictionary plan identity"),
        ],
    },
    {
        "key_builder": (f"{_OPS}/stream_scan.py",
                        "streaming_scan_filter.build"),
        "roots": [(f"{_OPS}/stream_scan.py",
                   "streaming_scan_filter.build")],
        "key_helpers": [(f"{_DOCDB}/operations.py",
                         "DocReadOperation._batch_cache_key")],
        "allow": {},
        "must_mention": [
            ("cache_key", "caller prefix (carries the batch key)"),
            ("chunk_rows", "runtime streaming_chunk_rows re-plan"),
            ("bucket", "pow2 pad bucket is part of batch shape"),
            ("prune_sig", "zone-pruned chunk list identity"),
            ("dict_sig", "dictionary plan identity"),
        ],
    },
    {
        "key_builder": (f"{_OPS}/plan_fusion.py",
                        "streaming_plan_aggregate.build"),
        "roots": [(f"{_OPS}/plan_fusion.py",
                   "streaming_plan_aggregate.build")],
        "key_helpers": [(f"{_DOCDB}/operations.py",
                         "DocReadOperation._batch_cache_key")],
        "allow": {},
        "must_mention": [
            ("cache_key", "caller prefix (carries the batch key)"),
            ("chunk_rows", "runtime streaming_chunk_rows re-plan"),
            ("bucket", "pow2 pad bucket is part of batch shape"),
        ],
    },
    {
        "key_builder": (f"{_OPS}/plan_fusion.py",
                        "monolithic_plan_aggregate"),
        "roots": [(f"{_OPS}/plan_fusion.py",
                   "monolithic_plan_aggregate")],
        "key_helpers": [(f"{_DOCDB}/operations.py",
                         "DocReadOperation._batch_cache_key")],
        "allow": {
            "zone_map_pruning": "captured via prune_key ('zp', "
                                "kept_idx) — the pruned block-list "
                                "identity, finer than the flag bit",
            "join_max_build_slots": "join runtime is rebuilt every "
                                    "call OUTSIDE the cached lambda — "
                                    "only build_batch(kept) is keyed",
            "multi_join_max_stages": "stage-count gate raises a typed "
                                     "JoinIneligible BEFORE any cache "
                                     "touch; runtimes are rebuilt "
                                     "every call outside the cached "
                                     "lambda",
        },
        "must_mention": [
            ("prune_key", "zone-pruned block list identity"),
        ],
    },
    {
        "key_builder": (f"{_OPS}/scan.py", "ScanKernel.run"),
        "roots": [(f"{_OPS}/scan.py", "ScanKernel.run")],
        "key_helpers": [],
        "allow": {
            "scan_group_strategy": "resolved value `strategy` is a "
                                   "signature component (finer: "
                                   "auto's resolution is keyed)",
            "tpu_pallas_scan": "dispatch gate only; pallas "
                               "eligibility memo keyed separately "
                               "under ('pallas', sig)",
        },
        "must_mention": [
            ("strategy", "grouped-path choice bakes into the kernel"),
            ("col_sig", "column dtype/shape identity"),
            ("mvcc_mode", "visibility mode changes the kernel body"),
        ],
    },
    {
        "key_builder": (f"{_OPS}/plan_fusion.py", "FusedPlanKernel.run"),
        "roots": [(f"{_OPS}/plan_fusion.py", "FusedPlanKernel.run")],
        "key_helpers": [],
        "allow": {
            "scan_group_strategy": "resolved value `strategy` is a "
                                   "signature component",
        },
        "must_mention": [
            ("strategy", "grouped-path choice bakes into the kernel"),
            ("col_sig", "column dtype/shape identity"),
            ("join_shape", "build-side shape identity"),
            ("build_buckets", "per-STAGE pow2 build buckets — a "
                              "multi-join chain must re-key when any "
                              "one stage crosses a table bucket"),
            ("dict_sig", "per-stage dict-coded payload lanes — which "
                         "lanes carry codes changes rewrite/decode "
                         "semantics downstream"),
            ("mvcc_mode", "visibility mode changes the kernel body"),
            ("static_sums", "const-folded sum lanes change the body"),
            ("padded_rows", "pow2 pad bucket is a compile-time shape"),
        ],
    },
)

_KEYISH = ("key", "sig")


def _keyish_name(name: str) -> bool:
    low = name.lower()
    return any(k in low for k in _KEYISH)


class CacheKeyCompletenessPass(AnalysisPass):
    id = "cache_key_completeness"
    title = "cache key missing a runtime input of the keyed computation"
    hint = ("add the input (or a derived signature of it) to the cache "
            "key, or record an allow reason in the pass registry "
            "explaining which key component already captures it")

    def __init__(self, registry: Optional[Sequence[dict]] = None):
        #: overridable so fixture tests can run synthetic registries
        self.registry: Tuple[dict, ...] = tuple(
            REGISTRY if registry is None else registry)

    def run(self, index: ProjectIndex) -> List[Finding]:
        graph = index.call_graph()
        out: List[Finding] = []

        #: per-def flags.get("<literal>") reads, for summarize()
        flag_reads: Dict[str, Dict[str, int]] = {}

        def direct(key: str) -> Dict[str, int]:
            if key in flag_reads:
                return flag_reads[key]
            d = graph.def_fact(key)
            got: Dict[str, int] = {}
            if d is not None:
                rel, qual = graph.split(key)
                mod = index.module(rel)
                node = self._def_node(index, graph, rel, qual)
                if mod is not None and node is not None:
                    for n in ast.walk(node):
                        if (isinstance(n, ast.Call)
                                and call_name(n).endswith("flags.get")
                                and n.args
                                and isinstance(n.args[0], ast.Constant)
                                and isinstance(n.args[0].value, str)):
                            got.setdefault(n.args[0].value, n.lineno)
            flag_reads[key] = got
            return got

        def follow(key: str) -> bool:
            return True

        for ent in self.registry:
            rel, qual = ent["key_builder"]
            mod = index.module(rel)
            node = self._def_node(index, graph, rel, qual)
            if mod is None or node is None:
                anchor = index.module(rel) or index.modules()[0]
                out.append(self.finding(
                    anchor, 1,
                    f"stale cache-key registry entry: def {qual!r} "
                    f"not found in {rel} — update the "
                    "cache_key_completeness registry",
                    detail=f"{rel}::{qual}"))
                continue

            key_text = self._key_text(qual, node)
            for hrel, hqual in ent["key_helpers"]:
                hnode = self._def_node(index, graph, hrel, hqual)
                if hnode is not None:
                    key_text += "\n" + ast.unparse(hnode)

            # 3. must-mention structural components
            for needle, why in ent["must_mention"]:
                if needle not in key_text:
                    out.append(self.finding(
                        mod, node.lineno,
                        f"cache key for {qual} lost its "
                        f"{needle!r} component ({why})",
                        detail=f"{qual}:{needle}"))

            # 2. flag closure over the keyed computation
            for rrel, rqual in ent["roots"]:
                rkey = graph.key(rrel, rqual)
                summ = graph.summarize(rkey, self.id, direct, follow)
                for flag in sorted(summ):
                    if flag in ent["allow"]:
                        continue
                    if f'"{flag}"' in key_text or \
                            f"'{flag}'" in key_text:
                        continue
                    steps = graph.chain(rkey, flag, self.id,
                                        direct, follow)
                    via = " -> ".join(
                        f"{q} ({r}:{ln})" for r, q, ln in steps)
                    out.append(self.finding(
                        mod, node.lineno,
                        f"keyed computation under {qual} reads flag "
                        f"{flag!r} (via {via or rqual}) but the cache "
                        "key never includes it — a runtime flag flip "
                        "serves stale cached results",
                        detail=f"{qual}:{flag}"))
        return out

    # ------------------------------------------------------------------
    @staticmethod
    def _def_node(index: ProjectIndex, graph, rel: str,
                  qual: str) -> Optional[ast.AST]:
        mod = index.module(rel)
        if mod is None or mod.tree is None:
            return None
        from ..callgraph import iter_defs
        for q, _cls, node in iter_defs(mod.tree):
            if q == qual:
                return node
        return None

    @staticmethod
    def _key_text(qual: str, node: ast.AST) -> str:
        """The key-building source of a def (see module docstring)."""
        name = qual.split(".")[-1]
        if _keyish_name(name):
            return ast.unparse(node)
        parts: List[str] = []
        # nested closures' key expressions count too: the chunk keys
        # are built inside `build` closures
        for n in ast.walk(node):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.args
                    and "cache" in ast.unparse(n.func.value).lower()):
                parts.append(ast.unparse(n.args[0]))
            if isinstance(n, ast.Assign):
                names: Set[str] = {
                    t.id for t in n.targets if isinstance(t, ast.Name)}
                if any(_keyish_name(x) for x in names):
                    parts.append(ast.unparse(n.value))
        return "\n".join(parts)


PASS = CacheKeyCompletenessPass()
