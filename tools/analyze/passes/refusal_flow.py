"""Pass: refusal-flow soundness — typed refusals must reach a typed
handler, never a broad ``except`` that swallows them.

The fast paths refuse work they cannot do exactly by RAISING a typed
refusal (BypassIneligible, DocIneligible, JoinIneligible,
PallasIneligible, MatviewIneligible, ...).  The contract is that every
refusal propagates to a dispatcher that catches the TYPE and routes the
request to the interpreted / CPU fallback.  A broad ``except
Exception:`` between the raise and that dispatcher launders the refusal
into "handled": the fast path silently returns garbage or caches a
wrong eligibility verdict, and the fallback never runs.  The raise and
the offending handler are usually several calls apart, so no lexical
pass can see the pair; this one follows the propagation
interprocedurally.

How it works:

1. REFUSAL CLASSES — every exception class defined in a module named
   ``errors.py``, every class named ``*Ineligible`` anywhere, and any
   class marked ``# analysis: refusal-class`` on its ``class`` line or
   the line above (for typed refusals that live outside an errors
   module, e.g. KeySuffixError).  Each class's catch-name set is its
   own name plus every ancestor name in its bases chain (project bases
   recursively, stdlib bases like ValueError by name) — so ``except
   ValueError`` legitimately catches KeySuffixError.
2. ESCAPE SETS — a memoized interprocedural walk computes, per def,
   the set of refusal classes that can propagate OUT of it: direct
   ``raise Refusal(...)`` statements plus calls whose resolved callee
   has a non-empty escape set, minus anything caught inside the def.
   Cycles and unresolvable calls under-approximate to empty
   (documented limit: no false positives from them).
3. HANDLER WALK — at each source point the enclosing ``try`` handlers
   are consulted innermost-out, in handler order, exactly like the
   interpreter would: a handler naming the refusal (or an ancestor)
   handles it; a BROAD handler (bare / ``Exception`` /
   ``BaseException``, including inside tuples) is the decision point —
   if its body re-raises (any ``raise``) the refusal propagates past;
   if its body mentions a refusal class name (the
   ``isinstance``-and-route shape) it counts as explicit handling;
   otherwise it is a FINDING at the handler line.
4. TASK-CANCEL SUB-RULE — ``task.cancel()`` without the
   cancel-until-done drain loses the cancellation entirely when it
   races an in-flight completion (bpo-37658), which is the same
   lost-control-flow shape at the event-loop level.  In async defs a
   bare ``.cancel()`` on a task-ish receiver (name contains "task",
   or assigned from ``create_task``/``ensure_future``, or iterating a
   task-named collection) is flagged unless it sits inside a
   ``while ... .done()`` drain loop.  Route new sites through
   ``yugabyte_db_tpu.utils.tasks.cancel_and_drain``.

Suppression anchors at the reported handler / cancel line:
``# analysis-ok(refusal_flow): <reason>``.
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..core import (AnalysisPass, Finding, ModuleInfo, ProjectIndex,
                    call_name)

#: names that make a handler "broad" rather than typed
_BROAD = frozenset({"Exception", "BaseException"})
#: base names stripped from catch sets (catching these is broad, not typed)
_NEVER_TYPED = frozenset({"Exception", "BaseException", "object"})

_REFUSAL_MARK = "# analysis: refusal-class"


def _terminal(expr: ast.expr) -> Optional[str]:
    """Last dotted component of a Name/Attribute chain, else None."""
    while isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _handler_names(h: ast.ExceptHandler) -> List[str]:
    """Terminal class names a handler catches; [] for a bare except."""
    t = h.type
    if t is None:
        return []
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for e in elts:
        n = _terminal(e)
        if n is not None:
            out.append(n)
    return out


class _Source:
    """Witness for one refusal entering a def: the raise itself or the
    call that lets it in."""
    __slots__ = ("line", "what")

    def __init__(self, line: int, what: str):
        self.line = line
        self.what = what


class RefusalFlowPass(AnalysisPass):
    id = "refusal_flow"
    title = "typed refusal swallowed by a broad except"
    hint = ("catch the refusal type explicitly (route to the fallback) "
            "before any broad except, or re-raise from the broad "
            "handler; for .cancel() use utils.tasks.cancel_and_drain")

    def run(self, index: ProjectIndex) -> List[Finding]:
        graph = index.call_graph()
        from ..callgraph import iter_defs

        refusals = self._discover(index, graph)
        self._catch: Dict[str, FrozenSet[str]] = {
            name: self._catch_names(graph, rel, qual)
            for name, (rel, qual) in refusals.items()}
        self._names: FrozenSet[str] = frozenset(refusals)

        #: def key -> (module, qual, ast node)
        self._defs: Dict[str, Tuple[ModuleInfo, str, ast.AST]] = {}
        for mod in index.modules():
            if mod.tree is None:
                continue
            for qual, _cls, node in iter_defs(mod.tree):
                self._defs[graph.key(mod.rel, qual)] = (mod, qual, node)

        self._graph = graph
        self._esc: Dict[str, FrozenSet[str]] = {}
        self._busy: Set[str] = set()
        #: (rel, handler line) -> (module, {refusal names}, witness)
        self._hits: Dict[Tuple[str, int],
                         Tuple[ModuleInfo, Set[str], _Source]] = {}

        for key in sorted(self._defs):
            self._escape(key)

        out: List[Finding] = []
        for (rel, line) in sorted(self._hits):
            mod, names, w = self._hits[(rel, line)]
            nm = ", ".join(sorted(names))
            out.append(self.finding(
                mod, line,
                f"broad except swallows typed refusal(s) {nm} "
                f"(reaches here from line {w.line}: {w.what}) without "
                "re-raising or routing to the fallback",
                detail=",".join(sorted(names))))
        out.extend(self._cancel_findings())
        return out

    # --- refusal-class discovery ------------------------------------------
    def _discover(self, index: ProjectIndex, graph,
                  ) -> Dict[str, Tuple[str, str]]:
        """name -> (rel, cls_qual) of every refusal class."""
        found: Dict[str, Tuple[str, str]] = {}
        for mod in index.modules():
            if mod.tree is None:
                continue
            f = graph.facts.get(mod.rel)
            if f is None:
                continue
            is_errors_mod = mod.rel.endswith("errors.py")
            for cq in f["classes"]:
                name = cq.split(".")[-1]
                if is_errors_mod or name.endswith("Ineligible"):
                    found.setdefault(name, (mod.rel, cq))
            # marker-declared refusals outside errors modules
            if _REFUSAL_MARK not in mod.source:
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                ln = node.lineno - 1          # 0-based
                here = mod.lines[ln] if ln < len(mod.lines) else ""
                above = mod.lines[ln - 1] if ln > 0 else ""
                if (_REFUSAL_MARK in here or _REFUSAL_MARK in above):
                    found.setdefault(node.name, (mod.rel, node.name))
        return found

    def _catch_names(self, graph, rel: str, cls_qual: str,
                     ) -> FrozenSet[str]:
        """Own name + every ancestor name: any of these in an except
        clause catches this refusal (minus the broad names)."""
        names: Set[str] = set()
        work = [(rel, cls_qual)]
        seen: Set[Tuple[str, str]] = set()
        while work:
            r, q = work.pop()
            if (r, q) in seen or len(seen) > 64:
                continue
            seen.add((r, q))
            names.add(q.split(".")[-1])
            c = graph.class_fact(r, q)
            if c is None:
                continue
            for b in c["bases"]:
                hit = graph.resolve_class(r, b)
                if hit is not None:
                    work.append(hit)
                else:
                    t = b.split(".")[-1]
                    if t:
                        names.add(t)
        return frozenset(names - _NEVER_TYPED)

    # --- escape sets + handler findings -----------------------------------
    def _escape(self, key: str) -> FrozenSet[str]:
        if key in self._esc:
            return self._esc[key]
        if key in self._busy:
            return frozenset()          # cycle: under-approximate
        ent = self._defs.get(key)
        if ent is None:
            return frozenset()
        self._busy.add(key)
        mod, qual, node = ent

        # fast path: a def with no raise and no except can only pass
        # its callees' escapes straight through — no AST walk needed
        # (the resolved edges come from the shared facts)
        end = getattr(node, "end_lineno", None) or node.lineno
        seg = "\n".join(mod.lines[node.lineno - 1:end])
        if "raise" not in seg and "except" not in seg:
            esc: Set[str] = set()
            for _line, _text, tgt in self._graph.edges(key):
                if tgt is not None and tgt != key:
                    esc |= self._escape(tgt)
            self._busy.discard(key)
            res = frozenset(esc)
            self._esc[key] = res
            return res

        escapes: Set[str] = set()

        def refusal_of(exc: Optional[ast.expr]) -> Optional[str]:
            if exc is None:
                return None
            tgt = exc.func if isinstance(exc, ast.Call) else exc
            n = _terminal(tgt)
            return n if n in self._names else None

        def propagate(names: Set[str], w: _Source,
                      tries: Tuple[ast.Try, ...]) -> None:
            live = set(names)
            for t in reversed(tries):
                if not live:
                    return
                for h in t.handlers:
                    hnames = _handler_names(h)
                    broad = (not hnames) or bool(set(hnames) & _BROAD)
                    typed_hit = {r for r in live
                                 if set(hnames) & self._catch[r]}
                    live -= typed_hit            # typed catch: handled
                    if not live:
                        return
                    if not broad:
                        continue
                    # broad handler reached with refusals still live
                    if self._reraises(h):
                        break                    # propagates past this try
                    if self._mentions_refusal(h, live):
                        return                   # isinstance-routed: handled
                    k = (mod.rel, h.lineno)
                    prev = self._hits.get(k)
                    if prev is None:
                        self._hits[k] = (mod, set(live), w)
                    else:
                        prev[1].update(live)
                    return                       # swallowed here
            escapes.update(live)

        def walk(n: ast.AST, tries: Tuple[ast.Try, ...]) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                return
            if isinstance(n, ast.Try):
                for s in n.body:
                    walk(s, tries + (n,))
                # handlers / else / finally are NOT covered by this
                # try's own handlers
                for h in n.handlers:
                    for s in h.body:
                        walk(s, tries)
                for s in n.orelse:
                    walk(s, tries)
                for s in n.finalbody:
                    walk(s, tries)
                return
            if isinstance(n, ast.Raise):
                r = refusal_of(n.exc)
                if r is not None:
                    propagate({r}, _Source(n.lineno, f"raise {r}"),
                              tries)
            elif isinstance(n, ast.Call):
                text = call_name(n)
                if text:
                    tgt = self._graph.resolve(mod.rel, qual, text)
                    if tgt is not None and tgt != key:
                        esc = self._escape(tgt)
                        if esc:
                            propagate(set(esc),
                                      _Source(n.lineno, f"{text}()"),
                                      tries)
            for c in ast.iter_child_nodes(n):
                walk(c, tries)

        for stmt in node.body:
            walk(stmt, ())
        self._busy.discard(key)
        res = frozenset(escapes)
        self._esc[key] = res
        return res

    @staticmethod
    def _reraises(h: ast.ExceptHandler) -> bool:
        """Any raise in the handler body (bare re-raise, re-raise of
        the bound name, or a translation raise) means the handler does
        not silently swallow."""
        for n in ast.walk(h):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Raise):
                return True
        return False

    def _mentions_refusal(self, h: ast.ExceptHandler,
                          live: Set[str]) -> bool:
        """Handler body references a live refusal's catch name — the
        ``isinstance(e, Refusal)``-and-route shape counts as typed
        handling."""
        wanted: Set[str] = set()
        for r in live:
            wanted |= self._catch[r]
        for n in ast.walk(h):
            if isinstance(n, ast.Name) and n.id in wanted:
                return True
            if isinstance(n, ast.Attribute) and n.attr in wanted:
                return True
        return False

    # --- task-cancel sub-rule ---------------------------------------------
    def _cancel_findings(self) -> List[Finding]:
        out: List[Finding] = []
        for key in sorted(self._defs):
            mod, qual, node = self._defs[key]
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            end = getattr(node, "end_lineno", None) or node.lineno
            if ".cancel(" not in "\n".join(
                    mod.lines[node.lineno - 1:end]):
                continue
            taskish = self._taskish_locals(node)

            def is_taskish(recv: ast.expr) -> bool:
                term = _terminal(recv)
                if term is None:
                    return False
                if "task" in term.lower():
                    return True
                return isinstance(recv, ast.Name) and recv.id in taskish

            def walk(n: ast.AST, in_drain: bool) -> None:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                    return
                if isinstance(n, ast.While):
                    drains = ".done()" in ast.unparse(n.test)
                    for c in ast.iter_child_nodes(n):
                        walk(c, in_drain or drains)
                    return
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "cancel"
                        and not n.args and not in_drain
                        and is_taskish(n.func.value)):
                    recv = ast.unparse(n.func.value)
                    out.append(self.finding(
                        mod, n.lineno,
                        f"bare {recv}.cancel() can lose the "
                        "cancellation when it races completion "
                        "(bpo-37658) — the task may keep running "
                        "after shutdown",
                        detail=f"{recv}.cancel"))
                for c in ast.iter_child_nodes(n):
                    walk(c, in_drain)

            for stmt in node.body:
                walk(stmt, False)
        return out

    @staticmethod
    def _taskish_locals(node: ast.AsyncFunctionDef) -> Set[str]:
        """Local names bound to tasks: assigned from create_task /
        ensure_future, or iterating a task-named collection."""
        names: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.Lambda)):
                continue
            if (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Call)):
                cn = call_name(n.value)
                if cn and (cn.endswith("create_task")
                           or cn.endswith("ensure_future")):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
            if isinstance(n, (ast.For, ast.AsyncFor)):
                it = ast.unparse(n.iter)
                if "task" in it.lower() and isinstance(n.target,
                                                       ast.Name):
                    names.add(n.target.id)
        return names


PASS = RefusalFlowPass()
