"""Pass: attributes mutated from both an executor thread and the loop.

The scheduler/compaction overlap creates exactly this bug shape: the
tserver ships ``tablet.compact`` / ``tablet.flush`` to
``run_in_executor`` threads while async handlers keep serving reads and
maintenance against the same object.  Any instance attribute both sides
mutate without a shared lock is a data race (list/dict corruption under
the C-API, torn multi-field invariants even under the GIL).

Two phases over the whole tree:

1. collect executor targets — the callables handed to
   ``run_in_executor(...)``, ``<pool>.submit(...)`` and
   ``threading.Thread(target=...)``.  Targets are RESOLVED through the
   project call graph to their actual defining class
   (``self.flush`` shipped from class C binds exactly ``C.flush`` —
   or the base class that defines it), so a class that merely shares a
   method NAME with somebody's executor target is no longer
   thread-side.  Only targets the graph cannot resolve
   (``peer.tablet.flush`` — receiver type unknown) fall back to the
   old terminal-name over-approximation.
2. per class: a sync method that is an executor target is THREAD-side;
   every async method is LOOP-side.  An attribute with an unlocked
   write on one side and any write on the other is a finding
   (locked-vs-unlocked still races — both sides must hold the lock).

Writes = ``self.X = / += ...``, ``self.X[...] = ...``, and mutator
calls (``self.X.append/update/pop/...``).  A write lexically inside
``with <lock>:`` / ``async with <lock>:`` counts as locked.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import (AnalysisPass, Finding, ModuleInfo, ProjectIndex,
                    call_name, is_lockish, terminal_attr)

_MUTATORS = {"append", "extend", "insert", "add", "update", "pop",
             "popitem", "clear", "remove", "discard", "sort",
             "appendleft", "popleft", "setdefault"}


def _expr_text(e: ast.expr) -> str:
    """Dotted text of a Name/Attribute chain ('self.flush'), '' when
    the expr is anything else."""
    parts: List[str] = []
    while isinstance(e, ast.Attribute):
        parts.append(e.attr)
        e = e.value
    if isinstance(e, ast.Name):
        parts.append(e.id)
        return ".".join(reversed(parts))
    return ""


def _executor_targets(index: ProjectIndex, mods: List[ModuleInfo],
                      ) -> Tuple[Set[Tuple[str, str, str]], Set[str]]:
    """(resolved, unresolved): resolved = (rel, class_qual, method) of
    every graph-resolvable executor target; unresolved = terminal
    names of the rest (the old over-approximation, kept only where
    resolution genuinely fails)."""
    from ..callgraph import iter_defs
    graph = index.call_graph()
    resolved: Set[Tuple[str, str, str]] = set()
    unresolved: Set[str] = set()

    def note(rel: str, qual: Optional[str], expr: ast.expr) -> None:
        if isinstance(expr, ast.Call):   # partial(self.m, ...) et al.
            if expr.args:
                note(rel, qual, expr.args[0])
            for kw in expr.keywords:
                note(rel, qual, kw.value)
            return
        if isinstance(expr, ast.Lambda):
            return   # no name to match; _scan_class reads its body
        text = _expr_text(expr)
        if text:
            tgt = graph.resolve(rel, qual, text)
            if tgt is not None:
                fact = graph.def_fact(tgt)
                if fact is not None and fact["cls"] is not None:
                    rel_t, _ = graph.split(tgt)
                    resolved.add((rel_t, fact["cls"], fact["name"]))
                    return
                if fact is not None:
                    return   # module-level fn: not a method, no class
        t = terminal_attr(expr)
        if t:
            unresolved.add(t)

    def scan_calls(rel: str, qual: Optional[str], body) -> None:
        def go(n: ast.AST) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                return          # nested defs scanned with their own qual
            if isinstance(n, ast.Call):
                fname = call_name(n)
                leaf = fname.split(".")[-1]
                if leaf == "run_in_executor" and len(n.args) >= 2:
                    note(rel, qual, n.args[1])
                elif leaf == "submit" and n.args and (
                        "executor" in fname.lower()
                        or "pool" in fname.lower()):
                    note(rel, qual, n.args[0])
                elif leaf == "Thread":
                    for kw in n.keywords:
                        if kw.arg == "target":
                            note(rel, qual, kw.value)
            for c in ast.iter_child_nodes(n):
                go(c)
        for s in body:
            go(s)

    for mod in mods:
        if mod.tree is None:
            continue
        module_level = [s for s in mod.tree.body]
        scan_calls(mod.rel, None, module_level)
        for qual, _cls, node in iter_defs(mod.tree):
            scan_calls(mod.rel, qual, node.body)
    # a subclass OVERRIDE of a shipped method is what actually runs on
    # the executor thread for subclass instances: every project class
    # that inherits from a resolved target's class and redefines the
    # method is thread-side too (resolution alone binds only the
    # MRO-defining class and would silently drop the override)
    for rel, f in graph.facts.items():
        for cq, c in f["classes"].items():
            for (r_t, c_t, m) in list(resolved):
                if m in c["methods"] and (rel, cq) != (r_t, c_t) \
                        and graph.is_subclass(rel, cq, r_t, c_t):
                    resolved.add((rel, cq, m))
    return resolved, unresolved


class _Write:
    __slots__ = ("attr", "line", "locked", "method")

    def __init__(self, attr: str, line: int, locked: bool, method: str):
        self.attr = attr
        self.line = line
        self.locked = locked
        self.method = method


def _collect_writes(fn, method: str) -> List[_Write]:
    out: List[_Write] = []

    def self_attr(expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return expr.attr
        return None

    def scan(node: ast.AST, locked: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = locked or any(is_lockish(i.context_expr)
                                  for i in node.items)
            for child in node.body:
                scan(child, inner)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                base = tgt
                if isinstance(base, (ast.Subscript,)):
                    base = base.value
                a = self_attr(base)
                if a:
                    out.append(_Write(a, node.lineno, locked, method))
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            a = self_attr(node.func.value)
            if a:
                out.append(_Write(a, node.lineno, locked, method))
        for child in ast.iter_child_nodes(node):
            scan(child, locked)

    for stmt in fn.body:
        scan(stmt, False)
    return out


def _iter_classes(tree: ast.Module):
    """Yield ``(cls_qual, ClassDef)`` with the call graph's qual
    scheme (nesting joined with '.') so resolved executor targets can
    be matched against the class being scanned."""

    def walk(stmts, scope):
        for s in stmts:
            if isinstance(s, ast.ClassDef):
                yield ".".join(scope + [s.name]), s
                yield from walk(s.body, scope + [s.name])
            elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from walk(s.body, scope + [s.name])
            else:
                children = [c for c in ast.iter_child_nodes(s)
                            if isinstance(c, (ast.stmt, ast.ExceptHandler,
                                              ast.match_case))]
                if children:
                    yield from walk(children, scope)

    yield from walk(tree.body, [])


def _executor_lambda(call: ast.Call) -> Optional[ast.Lambda]:
    """The Lambda handed to an executor in this call, if any —
    `run_in_executor(None, lambda: ...)` has no name for the phase-1
    target set, so its body is read directly where it appears."""
    fname = call_name(call)
    leaf = fname.split(".")[-1]
    cand: Optional[ast.expr] = None
    if leaf == "run_in_executor" and len(call.args) >= 2:
        cand = call.args[1]
    elif leaf == "submit" and call.args and (
            "executor" in fname.lower() or "pool" in fname.lower()):
        cand = call.args[0]
    elif leaf == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                cand = kw.value
    return cand if isinstance(cand, ast.Lambda) else None


def _lambda_writes(lam: ast.Lambda, method: str) -> List[_Write]:
    """Mutator calls on self attributes inside a lambda body (a lambda
    can't assign to attributes, so mutators are the only write form)."""
    out: List[_Write] = []
    for node in ast.walk(lam.body):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS \
                and isinstance(node.func.value, ast.Attribute) \
                and isinstance(node.func.value.value, ast.Name) \
                and node.func.value.value.id == "self":
            out.append(_Write(node.func.value.attr, node.lineno, False,
                              f"{method}:<lambda>"))
    return out


class SharedStateRacesPass(AnalysisPass):
    id = "shared_state_races"
    title = "attribute mutated from executor thread and event loop"
    hint = ("guard both sides with one threading.Lock (the loop side "
            "holds it only for the mutation, never across an await), "
            "or confine the attribute to one context")

    def run(self, index: ProjectIndex) -> List[Finding]:
        out: List[Finding] = []
        mods = index.modules()
        resolved, unresolved = _executor_targets(index, mods)
        # no early-out on an empty target set: inline executor lambdas
        # contribute thread-side writes without a name to match
        for mod in mods:
            if mod.tree is None:
                continue
            for cls_qual, node in _iter_classes(mod.tree):
                self._scan_class(mod, cls_qual, node, resolved,
                                 unresolved, out)
        return out

    def _scan_class(self, mod: ModuleInfo, cls_qual: str,
                    cls: ast.ClassDef,
                    resolved: Set[Tuple[str, str, str]],
                    unresolved: Set[str], out: List[Finding]) -> None:
        thread_writes: List[_Write] = []
        loop_writes: List[_Write] = []
        for item in cls.body:
            if isinstance(item, ast.FunctionDef) \
                    and ((mod.rel, cls_qual, item.name) in resolved
                         or item.name in unresolved) \
                    and item.name != "__init__":
                thread_writes.extend(_collect_writes(item, item.name))
            elif isinstance(item, ast.AsyncFunctionDef):
                loop_writes.extend(_collect_writes(item, item.name))
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # inline executor lambdas mutate on a thread no matter
                # which kind of method ships them
                for node in ast.walk(item):
                    if isinstance(node, ast.Call):
                        lam = _executor_lambda(node)
                        if lam is not None:
                            thread_writes.extend(
                                _lambda_writes(lam, item.name))
        if not thread_writes or not loop_writes:
            return
        by_attr_thread: Dict[str, List[_Write]] = {}
        for w in thread_writes:
            by_attr_thread.setdefault(w.attr, []).append(w)
        by_attr_loop: Dict[str, List[_Write]] = {}
        for w in loop_writes:
            by_attr_loop.setdefault(w.attr, []).append(w)
        for attr in sorted(set(by_attr_thread) & set(by_attr_loop)):
            tw = by_attr_thread[attr]
            lw = by_attr_loop[attr]
            unlocked = [w for w in tw + lw if not w.locked]
            if not unlocked:
                continue
            anchor = unlocked[0]
            t0, l0 = tw[0], lw[0]
            out.append(self.finding(
                mod, anchor.line,
                f"`{cls.name}.{attr}` is mutated from executor-target "
                f"`{t0.method}` (line {t0.line}) and async "
                f"`{l0.method}` (line {l0.line}) without a shared "
                f"lock on both sides",
                detail=f"{cls.name}.{attr}"))


PASS = SharedStateRacesPass()
