"""Pass: bypass-subsystem layering — ``bypass/`` must not import the
hot path it exists to avoid.

The analytics bypass engine's whole value proposition is STRUCTURAL
isolation from the tserver data path: pins come from the storage
layer, SST files open directly, kernels dispatch in the caller.  The
moment a ``yugabyte_db_tpu/bypass/`` module imports ``tserver``,
``sched`` or ``rpc`` — at module level or inside any function — that
guarantee is one refactor away from quietly becoming "bypass calls the
scheduler"; this pass makes the dependency direction a tier-1 fact
rather than a comment.

Detected shapes (absolute and relative spellings):

1. ``import yugabyte_db_tpu.tserver...`` / ``from yugabyte_db_tpu.rpc
   import ...`` anywhere in a bypass module.
2. ``from ..tserver import ...`` / ``from .. import sched`` — relative
   imports resolved against the module's package path.

Known lexical limits (same spirit as the other passes): only DIRECT
imports are checked — a transitive edge through an allowed layer
(e.g. docdb) is the imported layer's responsibility, and dynamic
``importlib`` indirection is invisible.  The forbidden set is a pass
constant so a future subsystem with its own layering rule can extend
the table rather than fork the pass.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import AnalysisPass, Finding, ProjectIndex

#: (scoped directory prefix -> module names its files must not import)
#:
#: - bypass/ exists to avoid the tserver hot path, so it must not
#:   import it (or the scheduler, or the rpc stack it sidesteps);
#: - cluster/ is the multi-process harness: it talks to servers ONLY
#:   over RPC and process signals, so it may import client/rpc/utils
#:   (and the models vocabulary) but never reach into server-side
#:   internals — importing tserver/tablet/storage would let the
#:   supervisor "fix" cluster state in-process, which is exactly the
#:   single-loop shortcut the subsystem exists to kill.
LAYER_RULES = {
    "yugabyte_db_tpu/bypass/": ("tserver", "sched", "rpc"),
    "yugabyte_db_tpu/cluster/": ("tserver", "tablet", "master", "sched",
                                 "storage", "consensus", "bypass",
                                 "docdb", "dockv", "ops"),
    # pure library: shredding/pushdown over storage+ops seams only —
    # may import storage/dockv/ops/utils (and docdb for the shared
    # expression rewrite), never server layers
    "yugabyte_db_tpu/docstore/": ("tserver", "tablet", "rpc"),
    # matview maintainers reach the cluster ONLY through client RPCs,
    # the CDC slot API and the ops combine seam (cdc/client/ops/utils/
    # models allowed) — importing server internals would let a
    # maintainer "fold" straight out of a tablet's memtable, which is
    # exactly the consistency shortcut the pinned-read-point + stream
    # design exists to kill
    "yugabyte_db_tpu/matview/": ("tserver", "tablet", "storage",
                                 "consensus"),
}

_PKG_ROOT = "yugabyte_db_tpu"


def _module_package(rel: str) -> List[str]:
    """Dotted package path of a repo-relative module file (the package
    containing it), e.g. yugabyte_db_tpu/bypass/scan.py ->
    ['yugabyte_db_tpu', 'bypass']."""
    parts = rel.replace("\\", "/").split("/")
    return parts[:-1]


def _resolve_relative(pkg: List[str], level: int, module: str) -> str:
    """Absolute dotted target of a level-N relative import from pkg."""
    base = pkg[:len(pkg) - (level - 1)] if level > 1 else list(pkg)
    return ".".join(base + ([module] if module else []))


class LayeringPass(AnalysisPass):
    id = "layering"
    title = "subsystem layering violations"
    hint = ("scoped subsystems keep their dependency direction: bypass "
            "takes data through storage/ops/parallel seams (never "
            "tserver/sched/rpc); cluster talks to servers only over "
            "RPC/client/signals (never server internals); matview "
            "folds through client/cdc/ops seams (never "
            "tserver/tablet/storage/consensus)")

    def _check_target(self, rel: str, forbidden, target: str):
        """First forbidden layer named by dotted import target, if
        any (targets are absolute, e.g. yugabyte_db_tpu.rpc.messenger
        or a bare top-level name)."""
        parts = target.split(".")
        if parts and parts[0] == _PKG_ROOT:
            parts = parts[1:]
        return parts[0] if parts and parts[0] in forbidden else None

    def run(self, index: ProjectIndex) -> List[Finding]:
        out: List[Finding] = []
        for mi in index.modules():
            rel = mi.rel.replace("\\", "/")
            rules = [layers for prefix, layers in LAYER_RULES.items()
                     if rel.startswith(prefix)]
            if not rules or mi.tree is None:
                continue
            forbidden = tuple(ly for layers in rules for ly in layers)
            pkg = _module_package(rel)
            for node in ast.walk(mi.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        hit = self._check_target(rel, forbidden, a.name)
                        if hit:
                            out.append(self._finding(mi, node, hit,
                                                     a.name))
                elif isinstance(node, ast.ImportFrom):
                    if node.level:
                        target = _resolve_relative(
                            pkg, node.level, node.module or "")
                    else:
                        target = node.module or ""
                    hit = self._check_target(rel, forbidden, target)
                    if hit is None:
                        # `from .. import rpc` / `from yugabyte_db_tpu
                        # import tserver` — the layer arrives as the
                        # imported NAME, not the module path
                        for a in node.names:
                            h2 = self._check_target(
                                rel, forbidden, f"{target}.{a.name}")
                            if h2:
                                hit = h2
                                target = f"{target}.{a.name}"
                                break
                    if hit:
                        out.append(self._finding(mi, node, hit, target))
        return out

    def _finding(self, mi, node, layer: str, target: str) -> Finding:
        rel = mi.rel.replace("\\", "/")
        sub = rel.split("/")[1] if "/" in rel else rel
        return self.finding(
            mi, node.lineno,
            f"{sub} module imports the `{layer}` layer "
            f"({target}) — the subsystem's isolation guarantee "
            "forbids this dependency",
            detail=f"{layer}:{target}")


PASS = LayeringPass()
