"""Pass: the event loop must not be parked while a SYNC lock is held.

Inside an async function, ``with self._lock: ... await ...`` parks the
coroutine while a *threading* lock stays held.  Every other task on the
loop that touches the same lock then blocks the whole loop (the classic
asyncio deadlock), and the critical section's invariants span an
arbitrary suspension point.  ``async with`` on an asyncio.Lock is the
correct spelling and is not flagged — awaiting under an async lock is
the normal cooperative pattern.

Two layers:

1. LEXICAL: an ``await`` anywhere inside a sync ``with`` statement
   whose context expression looks like a lock (terminal name matches
   lock/mutex/rlock), stopping at nested function boundaries.
2. TRANSITIVE (call-graph powered): a call under a held sync lock that
   resolves to a sync project def whose bounded-depth summary contains
   a STRONG blocking call (the ``async_blocking`` transitive set).
   A sync helper cannot await, but it CAN stall the whole loop with
   the lock held — every contender then queues behind a device/network
   stall instead of a few bytecodes.  The finding reports the helper
   chain; a blocking call suppressed at its own line does not taint.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import (AnalysisPass, Finding, ModuleInfo, ProjectIndex,
                    call_name, is_lockish, is_suppressed)
from .async_blocking import TRANSITIVE_BLOCKING, render_chain


class LockHeldAwaitPass(AnalysisPass):
    id = "lock_held_await"
    title = "await while holding a sync lock"
    hint = ("use `asyncio.Lock` + `async with`, or move the await out "
            "of the critical section")

    def run(self, index: ProjectIndex) -> List[Finding]:
        out: List[Finding] = []
        from ..callgraph import iter_defs
        graph = index.call_graph()

        def direct(key: str) -> Dict[str, int]:
            d = graph.def_fact(key)
            if d is None:
                return {}
            rel, _ = graph.split(key)
            m = index.module(rel)
            hits: Dict[str, int] = {}
            for line, text in d["calls"]:
                if text in TRANSITIVE_BLOCKING and text not in hits \
                        and m is not None \
                        and not is_suppressed(m, line, "async_blocking") \
                        and not is_suppressed(m, line, self.id):
                    hits[text] = line
            return hits

        def follow(key: str) -> bool:
            return not graph.is_async(key)

        for mod in index.modules():
            if mod.tree is None:
                continue
            for qual, _cls, node in iter_defs(mod.tree):
                if not isinstance(node, ast.AsyncFunctionDef):
                    continue
                for stmt in node.body:
                    self._scan(mod, stmt, None, qual, graph, direct,
                               follow, out)
        return out

    def _scan(self, mod: ModuleInfo, node: ast.AST, held: Optional[str],
              qual: str, graph, direct, follow,
              out: List[Finding], _seen: Optional[Set] = None) -> None:
        if _seen is None:
            _seen = set()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return      # a nested function's awaits run on its own call
        if isinstance(node, ast.With):
            lockish = [ast.unparse(i.context_expr) for i in node.items
                       if is_lockish(i.context_expr)]
            inner = held or (lockish[0] if lockish else None)
            for item in node.items:     # `with await acquire():` edge
                self._scan(mod, item, held, qual, graph, direct, follow,
                           out, _seen)
            for child in node.body:
                self._scan(mod, child, inner, qual, graph, direct,
                           follow, out, _seen)
            return
        if isinstance(node, ast.Await) and held is not None:
            out.append(self.finding(
                mod, node.lineno,
                f"await while holding sync lock `{held}` — other tasks "
                f"contending on it will block the event loop",
                detail=held))
            # keep walking: the awaited expression may nest more awaits
        if isinstance(node, ast.Call) and held is not None:
            self._check_call(mod, node, held, qual, graph, direct,
                             follow, out, _seen)
        for child in ast.iter_child_nodes(node):
            self._scan(mod, child, held, qual, graph, direct, follow,
                       out, _seen)

    def _check_call(self, mod: ModuleInfo, node: ast.Call, held: str,
                    qual: str, graph, direct, follow, out: List[Finding],
                    _seen: Set) -> None:
        text = call_name(node)
        if not text:
            return
        tgt = graph.resolve(mod.rel, qual, text)
        if tgt is None or graph.is_async(tgt):
            return
        summ = graph.summarize(tgt, "lock_held_blocking", direct, follow)
        for bname in sorted(summ):
            sig = (mod.rel, node.lineno, held, bname)
            if sig in _seen:
                continue
            _seen.add(sig)
            hops = graph.chain(tgt, bname, "lock_held_blocking",
                               direct, follow)
            out.append(self.finding(
                mod, node.lineno,
                f"blocking call `{bname}` reached while holding sync "
                f"lock `{held}` — every contender queues behind the "
                f"stall: {render_chain(graph, text, hops, bname)}",
                detail=f"{held}->{bname}"))


PASS = LockHeldAwaitPass()
