"""Pass: ``await`` while holding a SYNC lock.

Inside an async function, ``with self._lock: ... await ...`` parks the
coroutine while a *threading* lock stays held.  Every other task on the
loop that touches the same lock then blocks the whole loop (the classic
asyncio deadlock), and the critical section's invariants span an
arbitrary suspension point.  ``async with`` on an asyncio.Lock is the
correct spelling and is not flagged — awaiting under an async lock is
the normal cooperative pattern.

The pass is lexical: an ``await`` anywhere inside a sync ``with``
statement whose context expression looks like a lock (terminal name
matches lock/mutex/rlock), stopping at nested function boundaries.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import (AnalysisPass, Finding, ModuleInfo, ProjectIndex,
                    is_lockish)


class LockHeldAwaitPass(AnalysisPass):
    id = "lock_held_await"
    title = "await while holding a sync lock"
    hint = ("use `asyncio.Lock` + `async with`, or move the await out "
            "of the critical section")

    def run(self, index: ProjectIndex) -> List[Finding]:
        out: List[Finding] = []
        for mod in index.modules():
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.AsyncFunctionDef):
                    for stmt in node.body:
                        self._scan(mod, stmt, None, out)
        return out

    def _scan(self, mod: ModuleInfo, node: ast.AST, held: str,
              out: List[Finding]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return      # a nested function's awaits run on its own call
        if isinstance(node, ast.With):
            lockish = [ast.unparse(i.context_expr) for i in node.items
                       if is_lockish(i.context_expr)]
            inner = held or (lockish[0] if lockish else None)
            for item in node.items:     # `with await acquire():` edge
                self._scan(mod, item, held, out)
            for child in node.body:
                self._scan(mod, child, inner, out)
            return
        if isinstance(node, ast.Await) and held is not None:
            out.append(self.finding(
                mod, node.lineno,
                f"await while holding sync lock `{held}` — other tasks "
                f"contending on it will block the event loop",
                detail=held))
            # keep walking: the awaited expression may nest more awaits
        for child in ast.iter_child_nodes(node):
            self._scan(mod, child, held, out)


PASS = LockHeldAwaitPass()
