"""Pass: lock-acquisition-order cycles — the deadlock shape the
compaction-executor / async-handler overlap keeps inviting.

If one code path acquires lock A then (still holding A) lock B, and
another path acquires B then A, the two paths deadlock the moment they
interleave — a sync pair across two executor threads wedges both
threads; a sync lock on the event loop against an executor thread
wedges the WHOLE server (every lane's dispatch shares that loop).
The order relation is global and crosses function boundaries, so no
lexical pass can see it; this one builds the project-wide
lock-acquisition-order graph and flags every cycle.

How it works:

1. ACQUISITION SITES — every ``with <lock>:`` / ``async with <lock>:``
   whose context expression looks like a lock (core.is_lockish).
   Sync and async locks both participate: an asyncio.Lock cycle
   deadlocks tasks exactly like a threading.Lock cycle deadlocks
   threads.
2. LOCK IDENTITY — ``self.X`` normalizes to the MRO class that
   assigns ``self.X`` (a base-class lock acquired from two subclasses
   is ONE lock; same-named attrs on unrelated classes are different
   locks); module globals normalize through the import table; any
   expression that is not a plain name chain (``self._locks[k]``) is
   scoped to its function so textual coincidence across functions can
   never fabricate an edge.
3. EDGES — acquiring B while A is held adds A->B.  Lexical nesting
   gives direct edges; a CALL made while holding A adds A->B for
   every lock B in the callee's bounded-depth transitive acquisition
   summary (sync and async callees both followed).
4. CYCLES — strongly connected components of the order graph; every
   SCC with two or more locks produces one finding anchored at its
   first acquisition edge, with the full cycle and each edge's
   acquisition site in the message.  Self-edges are NOT flagged:
   re-acquiring the same name is usually an RLock and name-based
   analysis cannot tell (documented limit).

Suppression anchors at the reported acquisition line:
``# analysis-ok(lock_order): <reason>``.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import (AnalysisPass, Finding, ModuleInfo, ProjectIndex,
                    call_name, is_lockish)

_PLAIN = frozenset("abcdefghijklmnopqrstuvwxyz"
                   "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._")


class _Edge:
    __slots__ = ("rel", "line", "qual", "via")

    def __init__(self, rel: str, line: int, qual: str,
                 via: Optional[str]):
        self.rel = rel          # module of the acquisition that closed
        self.line = line        # the edge (the B-acquire site)
        self.qual = qual        # def it happens in
        self.via = via          # call text when the edge is transitive


class LockOrderPass(AnalysisPass):
    id = "lock_order"
    title = "lock-acquisition-order cycle (deadlock)"
    hint = ("acquire the locks in one global order everywhere (or "
            "collapse the pair into a single lock); see the cycle "
            "sites in the message")

    def run(self, index: ProjectIndex) -> List[Finding]:
        from ..callgraph import iter_defs
        graph = index.call_graph()
        #: def key -> {lock_id: first-acquisition line}
        def_locks: Dict[str, Dict[str, int]] = {}
        #: (a, b) -> first witness edge
        edges: Dict[Tuple[str, str], _Edge] = {}
        #: deferred transitive checks: (key, line, text, held-snapshot)
        pending: List[Tuple[str, int, str, Tuple[str, ...]]] = []

        for mod in index.modules():
            if mod.tree is None:
                continue
            for qual, _cls, node in iter_defs(mod.tree):
                key = graph.key(mod.rel, qual)
                acq = def_locks.setdefault(key, {})
                self._scan_def(graph, mod, qual, node, acq, edges,
                               pending)

        def direct(key: str) -> Dict[str, int]:
            return def_locks.get(key, {})

        def follow(key: str) -> bool:
            return True          # async callees order locks too

        for key, line, text, held in pending:
            rel, qual = graph.split(key)
            tgt = graph.resolve(rel, qual, text)
            if tgt is None:
                continue
            summ = graph.summarize(tgt, self.id, direct, follow)
            for lid in summ:
                for h in held:
                    if h != lid and (h, lid) not in edges:
                        edges[(h, lid)] = _Edge(rel, line, qual, text)

        return self._cycle_findings(index, edges)

    # --- per-def lexical scan ---------------------------------------------
    def _scan_def(self, graph, mod: ModuleInfo, qual: str, node,
                  acq: Dict[str, int],
                  edges: Dict[Tuple[str, str], _Edge],
                  pending: List) -> None:
        key = graph.key(mod.rel, qual)

        def walk(n: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                return
            if isinstance(n, (ast.With, ast.AsyncWith)):
                inner = held
                for item in n.items:
                    walk(item.context_expr, inner)
                    if is_lockish(item.context_expr):
                        lid = self._lock_id(graph, mod.rel, qual,
                                            item.context_expr)
                        if lid not in acq:
                            acq[lid] = n.lineno
                        for h in inner:
                            if h != lid and (h, lid) not in edges:
                                edges[(h, lid)] = _Edge(
                                    mod.rel, n.lineno, qual, None)
                        if lid not in inner:
                            inner = inner + (lid,)
                for child in n.body:
                    walk(child, inner)
                return
            if isinstance(n, ast.Call) and held:
                text = call_name(n)
                if text:
                    pending.append((key, n.lineno, text, held))
            for c in ast.iter_child_nodes(n):
                walk(c, held)

        for stmt in node.body:
            walk(stmt, ())

    # --- lock identity ----------------------------------------------------
    def _lock_id(self, graph, rel: str, def_qual: str,
                 expr: ast.expr) -> str:
        text = ast.unparse(expr)
        if not set(text) <= _PLAIN:
            # subscripts / calls / anything computed: function-scoped,
            # so textual coincidence across functions can't alias
            return f"{rel}::{def_qual}:{text}"
        parts = text.split(".")
        f = graph.facts.get(rel)
        if parts[0] in ("self", "cls"):
            cls = None
            if f is not None:
                d = f["defs"].get(def_qual)
                cls = (d["cls"] if d and d["cls"]
                       else graph._enclosing_class(rel, def_qual))
            if cls is None:
                return f"{rel}::{def_qual}:{text}"
            if len(parts) == 2:
                r2, c2 = graph.defining_class(rel, cls, parts[1])
                return f"{r2}::{c2}.{parts[1]}"
            return f"{rel}::{cls}.{'.'.join(parts[1:])}"
        if len(parts) == 1:
            if f is not None and parts[0] in f["globals"]:
                return f"{rel}::{parts[0]}"
            return f"{rel}::{def_qual}:{parts[0]}"
        if f is not None and parts[0] in f["imports"]:
            target = f["imports"][parts[0]] + "." + ".".join(parts[1:])
            tparts = target.split(".")
            for i in range(len(tparts) - 1, 0, -1):
                rel2 = graph.mod_rel.get(".".join(tparts[:i]))
                if rel2 is not None:
                    return f"{rel2}::{'.'.join(tparts[i:])}"
        return f"{rel}::{text}"

    # --- cycle detection --------------------------------------------------
    def _cycle_findings(self, index: ProjectIndex,
                        edges: Dict[Tuple[str, str], _Edge],
                        ) -> List[Finding]:
        adj: Dict[str, List[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        for v in adj.values():
            v.sort()
        sccs = _tarjan(adj)
        out: List[Finding] = []
        for comp in sorted((sorted(c) for c in sccs if len(c) > 1)):
            cyc = _find_cycle(comp, adj)
            if not cyc:
                continue
            cyc_edges = [(cyc[i], cyc[(i + 1) % len(cyc)])
                         for i in range(len(cyc))]
            witnesses = [edges[e] for e in cyc_edges if e in edges]
            if not witnesses:
                continue
            anchor = min(witnesses, key=lambda w: (w.rel, w.line))
            mod = index.module(anchor.rel)
            if mod is None:
                continue
            steps = []
            for (a, b), w in zip(cyc_edges, witnesses):
                via = f" via {w.via}()" if w.via else ""
                steps.append(f"`{_short(a)}` -> `{_short(b)}` "
                             f"({w.rel}:{w.line} in {w.qual}{via})")
            out.append(self.finding(
                mod, anchor.line,
                "lock-order cycle — these paths deadlock when they "
                "interleave: " + "; ".join(steps),
                detail=" -> ".join(_short(x) for x in
                                   cyc + [cyc[0]])))
        return out


def _short(lock_id: str) -> str:
    return lock_id.split("::", 1)[-1]


def _tarjan(adj: Dict[str, List[str]]) -> List[List[str]]:
    """Iterative Tarjan SCC (the lock graph is small, but no pass may
    depend on the recursion limit)."""
    idx: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Dict[str, bool] = {}
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in idx:
            continue
        work = [(root, iter(adj[root]))]
        idx[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on[root] = True
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on[w] = True
                    work.append((w, iter(adj[w])))
                    advanced = True
                    break
                if on.get(w):
                    low[v] = min(low[v], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == idx[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on[w] = False
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)
    return sccs


def _find_cycle(comp: List[str],
                adj: Dict[str, List[str]]) -> List[str]:
    """One simple cycle through an SCC, starting at its smallest
    member (deterministic)."""
    comp_set = set(comp)
    start = comp[0]
    path: List[str] = [start]
    seen = {start}

    def dfs(v: str) -> Optional[List[str]]:
        for w in adj.get(v, ()):
            if w == start and len(path) > 1:
                return list(path)
            if w in comp_set and w not in seen:
                seen.add(w)
                path.append(w)
                r = dfs(w)
                if r is not None:
                    return r
                path.pop()
        return None

    return dfs(start) or []


PASS = LockOrderPass()
