#!/bin/bash
# TPU-window watchdog: probe the axon tunnel every PROBE_INTERVAL
# seconds (default 900 — round 2 proved windows can be ~20 minutes, so
# hourly is too coarse); the moment a probe succeeds, fire tpu_smoke.py
# (<5 min of device time, appends to TPU_RESULTS.md) and then the full
# bench.py, and attempt to commit the evidence.  Every attempt is
# logged to TPU_PROBE_LOG.jsonl so "zero windows" is provable.
#
# Re-runs the full pipeline only when HEAD moved since the last
# successful on-device run (state in .tpu_probe_state, written with the
# POST-commit HEAD so the watchdog's own evidence commit doesn't
# re-trigger itself).  Failed smoke runs (device up, check failed) are
# committed too — failure evidence is still evidence — and advance the
# state so the same failure isn't re-appended every interval.
set -u
cd "$(dirname "$0")/.."
REPO="$PWD"
LOG="$REPO/TPU_PROBE_LOG.jsonl"
STATE="$REPO/.tpu_probe_state"
INTERVAL="${PROBE_INTERVAL:-900}"

log() {  # log '{"k":"v"}'-style JSON fields
    echo "{\"ts\": \"$(date -u +%FT%TZ)\", $1}" >> "$LOG"
}

while true; do
    out=$(timeout 150 python -c "
import jax
d = jax.devices()
print(d[0])" 2>/dev/null)
    rc=$?
    if [ $rc -ne 0 ] || echo "$out" | grep -qi cpu; then
        log "\"probe\": \"down\", \"rc\": $rc"
        sleep "$INTERVAL"
        continue
    fi
    head=$(git rev-parse --short HEAD 2>/dev/null)
    last=$(cat "$STATE" 2>/dev/null || echo none)
    if [ "$head" = "$last" ]; then
        log "\"probe\": \"up\", \"device\": \"$out\", \"action\": \"already-validated-at-$head\""
        sleep "$INTERVAL"
        continue
    fi
    log "\"probe\": \"up\", \"device\": \"$out\", \"action\": \"smoke+bench\""
    SMOKE_SKIP_PROBE=1 timeout 900 python tpu_smoke.py \
        > "$REPO/.tpu_smoke_last.json" 2> "$REPO/.tpu_smoke_last.err"
    smoke_rc=$?
    log "\"smoke_rc\": $smoke_rc"
    if [ $smoke_rc -eq 2 ]; then
        # probe said up but smoke saw no device (window closed mid-way)
        sleep "$INTERVAL"
        continue
    fi
    commit_files="TPU_RESULTS.md TPU_PROBE_LOG.jsonl"
    msg="On-device TPU evidence: tpu_smoke (rc=$smoke_rc) at $head"
    if [ $smoke_rc -eq 0 ]; then
        # full bench (bounded; the smoke evidence is already on disk)
        timeout 3600 python bench.py > "$REPO/BENCH_tpu_live.json" \
            2> "$REPO/.bench_tpu_live.err"
        bench_rc=$?
        log "\"bench_rc\": $bench_rc"
        if [ $bench_rc -eq 0 ] && [ -s "$REPO/BENCH_tpu_live.json" ]; then
            commit_files="$commit_files BENCH_tpu_live.json"
            msg="On-device TPU evidence: tpu_smoke + bench at $head"
        fi
    fi
    git add $commit_files 2>/dev/null
    git commit -m "$msg" 2>/dev/null \
        && log "\"committed\": true" || log "\"committed\": false"
    # post-commit HEAD: the evidence commit must not re-trigger a run
    git rev-parse --short HEAD > "$STATE" 2>/dev/null
    sleep "$INTERVAL"
done
