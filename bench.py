#!/usr/bin/env python
"""Benchmark driver: TPC-H-style scan pushdown on the TPU engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Primary metric (BASELINE.json config 2/3): TPC-H Q6 rows/sec through the
TPU scan path on one tablet, vs the vectorized-numpy CPU baseline over
the identical columnar blocks (a fair stand-in for a good CPU engine —
NOT the row-at-a-time interpreter). Extra fields report Q1 grouped
aggregation and the device compaction merge.

Env knobs: BENCH_SF (default 1.0), BENCH_REPEATS (default 5).
"""
import json
import os
import sys
import tempfile
import time

import numpy as np


def best_of(fn, n, *args):
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        ts.append(time.perf_counter() - t0)
    return min(ts), out


def probe_device(timeout_s: int = 180) -> bool:
    """Check the accelerator actually responds before committing the
    process to it (the tunneled TPU can wedge — a hung jax.devices()
    would otherwise hang the whole benchmark). Probed in a subprocess so
    a hang can be killed."""
    import subprocess
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp;"
             "print(float(jnp.ones((8, 8)).sum()))"],
            timeout=timeout_s, capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    repeats = int(os.environ.get("BENCH_REPEATS", "5"))

    device_fallback = False
    if not os.environ.get("YBTPU_PLATFORM") and not probe_device():
        # accelerator unreachable: still produce a benchmark line on CPU
        os.environ["YBTPU_PLATFORM"] = "cpu"
        device_fallback = True

    import jax
    from yugabyte_db_tpu.models.tpch import (
        LineitemTable, TPCH_Q1, TPCH_Q6, generate_lineitem, numpy_reference,
    )
    from yugabyte_db_tpu.ops.cpu_scan import cpu_scan_aggregate
    from yugabyte_db_tpu.ops.device_batch import build_batch
    from yugabyte_db_tpu.ops.scan import ScanKernel
    from yugabyte_db_tpu.utils import flags

    dev = jax.devices()[0]
    data = generate_lineitem(sf)
    n = len(data["rowid"])

    tmp = tempfile.mkdtemp(prefix="ybtpu-bench-")
    table = LineitemTable(tmp, num_tablets=1)
    t0 = time.perf_counter()
    loaded = table.load(data)
    load_s = time.perf_counter() - t0
    tablet = table.tablets[0]

    blocks = []
    for r in tablet.regular.ssts:
        for i in range(r.num_blocks()):
            blocks.append(r.columnar_block(i))

    results = {}
    kernel = ScanKernel()
    for q in (TPCH_Q6, TPCH_Q1):
        # CPU vectorized baseline over the same blocks
        cpu_t, cpu_out = best_of(
            lambda: cpu_scan_aggregate(blocks, q.columns, q.where, q.aggs,
                                       q.group), max(2, repeats // 2))
        # TPU path: device-resident batch (block cache steady state)
        batch = build_batch(blocks, sorted(q.columns))
        def tpu_run():
            outs, counts, _ = kernel.run(batch, q.where, q.aggs, q.group)
            jax.block_until_ready(outs)
            return outs
        tpu_run()  # compile + warm
        tpu_t, tpu_out = best_of(tpu_run, repeats)
        # correctness spot check vs direct numpy
        ref = numpy_reference(q, data)
        if q.name == "q6":
            rel = abs(float(tpu_out[0]) - ref) / max(abs(ref), 1e-9)
            assert rel < 1e-3, f"q6 mismatch: {float(tpu_out[0])} vs {ref}"
        results[q.name] = {
            "cpu_s": cpu_t, "tpu_s": tpu_t,
            "cpu_rows_per_s": n / cpu_t, "tpu_rows_per_s": n / tpu_t,
            "speedup": cpu_t / tpu_t,
        }

    # compaction merge micro-bench: device merge of the loaded SST against
    # an overlapping second version of 10% of rows
    from yugabyte_db_tpu.docdb.compaction import tpu_compact
    upd = {k: v[: n // 10] for k, v in data.items()}
    from yugabyte_db_tpu.utils.hybrid_time import HybridTime
    tablet.bulk_load(upd, ht=HybridTime.from_micros(
        int(time.time() * 1e6) + 10_000_000))
    total_bytes = tablet.approximate_size()
    t0 = time.perf_counter()
    tablet.compact()
    comp_s = time.perf_counter() - t0
    results["compaction"] = {
        "input_mb": total_bytes / 1e6,
        "mb_per_s": total_bytes / 1e6 / comp_s,
        "seconds": comp_s,
    }

    # YCSB workload C (BASELINE config 1): engine-level point reads
    from yugabyte_db_tpu.models.ycsb import YcsbTabletWorkload, usertable_info
    from yugabyte_db_tpu.tablet import Tablet
    yt = Tablet("ycsb", usertable_info(), tempfile.mkdtemp(prefix="ycsb-"))
    w = YcsbTabletWorkload(yt, n_rows=100_000)
    w.load()
    rc = w.run("c", ops=int(os.environ.get("BENCH_YCSB_OPS", "2000")))
    results["ycsb_c"] = {"ops_per_s": rc.ops_per_sec}

    # Vector search micro (BASELINE config 5 at reduced scale by default;
    # BENCH_FULL=1 runs 1M x 768)
    from yugabyte_db_tpu.ops.vector import IvfFlatIndex
    full = os.environ.get("BENCH_FULL") == "1"
    vn, vd = (1_000_000, 768) if full else (200_000, 128)
    rngv = np.random.default_rng(0)
    base = rngv.normal(size=(vn, vd)).astype(np.float32)
    t0 = time.perf_counter()
    idx = IvfFlatIndex.build(base, nlists=200 if full else 64, iters=5)
    build_s = time.perf_counter() - t0
    q = base[:64] + 0.001
    idx.search(q, k=10, nprobe=8)   # warm/compile
    t0 = time.perf_counter()
    for _ in range(5):
        idx.search(q, k=10, nprobe=8)
    search_s = (time.perf_counter() - t0) / 5
    results["vector"] = {
        "n": vn, "dim": vd, "build_s": build_s,
        "qps": 64 / search_s,
    }

    q6 = results["q6"]
    line = {
        "metric": "tpch_q6_sf%g_tpu_rows_per_sec" % sf,
        "value": round(q6["tpu_rows_per_s"], 1),
        "unit": "rows/s",
        "vs_baseline": round(q6["speedup"], 3),
        "device": str(dev) + (" (FALLBACK: accelerator unreachable)"
                              if device_fallback else ""),
        "rows": n,
        "load_rows_per_s": round(loaded / load_s, 1),
        "q1": {"tpu_rows_per_s": round(results["q1"]["tpu_rows_per_s"], 1),
               "speedup": round(results["q1"]["speedup"], 3)},
        "compaction_mb_per_s": round(results["compaction"]["mb_per_s"], 2),
        "ycsb_c_ops_per_s": round(results["ycsb_c"]["ops_per_s"], 1),
        "vector": {"n": results["vector"]["n"],
                   "dim": results["vector"]["dim"],
                   "build_s": round(results["vector"]["build_s"], 2),
                   "search_qps": round(results["vector"]["qps"], 1)},
    }
    print(json.dumps(line))


if __name__ == "__main__":
    main()
