#!/usr/bin/env python
"""Benchmark driver: TPC-H-style scan pushdown on the TPU engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Covers the BASELINE.json configs:
  1. YCSB-C engine-level point reads
  2. TPC-H Q6 single tablet (primary metric; rows/s, vs vectorized-numpy
     CPU baseline over the identical columnar blocks — a fair stand-in
     for a good CPU engine, NOT a row-at-a-time interpreter)
  3. TPC-H Q1 distributed over 8 tablets with psum combine (falls back
     to host-side combine when fewer than 8 devices exist)
  4. Major compaction of a many-SSTable tablet, device merge vs CPU feed
  5. Vector search (IVF-flat; BENCH_FULL=1 runs the 1M x 768 config)

Q6 AND Q1 results are verified against direct-numpy references.

Env knobs: BENCH_SF (default 1.0), BENCH_REPEATS (default 5),
BENCH_COMPACT_SSTS (default 100), BENCH_COMPACT_ROWS (rows per SST,
default 20000), BENCH_YCSB_OPS, BENCH_FULL.
"""
import json
import os
import sys
import tempfile
import time

import numpy as np


def _vector_line(v):
    """Vector result block: rates plus the parameters they were bought
    with (nlists/nprobe/candidate-pool/ef and kernel-compile counts)."""
    return {"n": v["n"], "dim": v["dim"],
            "build_s": round(v["build_s"], 2),
            "nlists": v["nlists"], "nprobe": v["nprobe"],
            "candidate_pool": v["candidate_pool"], "ef": v["ef"],
            "kernel_cache": v["kernel_cache"],
            "search_qps": round(v["qps"], 1),
            "recall_at_10": round(v["recall_at_10"], 3)}


def best_of(fn, n, *args):
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        ts.append(time.perf_counter() - t0)
    return min(ts), out


def _probe_cache_path() -> str:
    return os.environ.get(
        "BENCH_PROBE_CACHE",
        os.path.join(tempfile.gettempdir(), "ybtpu_device_probe.json"))


def probe_device(timeouts=None):
    """Check the accelerator actually responds before committing the
    process to it (the tunneled TPU can wedge — a hung jax.devices()
    would otherwise hang the whole benchmark). Probed in a subprocess so
    a hang can be killed, with ONE short bounded attempt (r05 burned
    540s re-probing a wedged tunnel with escalating timeouts). The
    verdict is cached to a file (BENCH_PROBE_CACHE, default
    $TMPDIR/ybtpu_device_probe.json) so every later bench/profile run in
    the environment reuses it instead of re-probing; the cached verdict
    is recorded in the output JSON as {"cached": true, ...}. Delete the
    cache file (or set BENCH_PROBE_CACHE=/dev/null) to force a fresh
    probe. BENCH_PROBE_TIMEOUTS overrides (comma-separated seconds; '0'
    skips probing and goes straight to CPU)."""
    import glob
    import subprocess
    env_t = os.environ.get("BENCH_PROBE_TIMEOUTS")
    if env_t is not None:
        try:
            timeouts = [int(x) for x in env_t.split(",") if x.strip()]
        except ValueError:
            timeouts = None     # malformed: keep the defaults
        if timeouts == [0]:
            return False, [{"skipped": "BENCH_PROBE_TIMEOUTS=0"}]
    cache_path = _probe_cache_path()
    if timeouts is None:
        # only default probes consult the cache — an explicit timeouts
        # argument (tpu_smoke.py's long-patience probe) means the caller
        # wants a fresh answer. Verdicts age out asymmetrically: a
        # positive lasts 1h (long enough to cover one bench/profile
        # run, short enough that a tunnel that wedges afterwards gets
        # re-probed by the KILLABLE subprocess instead of hanging the
        # main process); a negative lasts 6h (being wrong only costs a
        # CPU fallback, and one short failed probe shouldn't pin the
        # environment to CPU forever either).
        try:
            with open(cache_path) as f:
                cached = json.load(f)
            age = time.time() - cached.get("probed_at", 0)
            fresh = age < (3600 if cached.get("ok") is True
                           else 6 * 3600)
            if isinstance(cached.get("ok"), bool) and fresh:
                return cached["ok"], [{"cached": True,
                                       "cache_path": cache_path,
                                       "probed_at": cached.get("probed_at"),
                                       "attempts": cached.get("attempts")}]
        except (OSError, ValueError):
            pass
    timeouts = timeouts or (75,)
    accel = sorted(glob.glob("/dev/accel*")) or ["<none>"]
    attempts = [{"dev_accel": accel,
                 "jax_platforms_env": os.environ.get("JAX_PLATFORMS", "")}]
    ok = False
    for t in timeouts:
        t0 = time.time()
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp;"
                 "d = jax.devices();"
                 "print(float(jnp.ones((8, 8)).sum()), d[0])"],
                timeout=t, capture_output=True)
            ok = r.returncode == 0
            err = (r.stderr or b"")[-300:].decode("utf-8", "replace") \
                if not ok else ""
            dev = (r.stdout or b"").decode("utf-8", "replace").strip()
        except subprocess.TimeoutExpired:
            ok, err, dev = False, f"hung past {t}s (killed)", ""
        attempts.append({"timeout_s": t, "ok": ok,
                         "elapsed_s": round(time.time() - t0, 1),
                         **({"device": dev} if ok else {}),
                         **({"error": err} if err else {})})
        if ok:
            break
    try:
        with open(cache_path, "w") as f:
            json.dump({"ok": ok, "probed_at": time.time(),
                       "attempts": attempts}, f)
    except OSError:
        pass
    return ok, attempts


def ycsb_overload_bench():
    """YCSB-C at 2x saturation through the REAL RPC path, scheduler ON
    vs OFF (the PR-3 headline): an open loop offers 2x the measured
    closed-loop saturation rate; ON must hold p99 via bounded queues +
    typed sheds (SERVICE_UNAVAILABLE + retry_after_ms) where OFF lets
    the backlog stack into seconds of latency.  Returns the comparison
    dict (or {"error": ...}); BENCH_OVERLOAD_S=0 skips."""
    import asyncio

    duration = float(os.environ.get("BENCH_OVERLOAD_S", "2.5"))
    if duration <= 0:
        return None

    async def run():
        from yugabyte_db_tpu.docdb.operations import ReadRequest
        from yugabyte_db_tpu.docdb.wire import read_request_to_wire
        from yugabyte_db_tpu.models.ycsb import usertable_info
        from yugabyte_db_tpu.rpc.messenger import Messenger, RpcError
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
        from yugabyte_db_tpu.utils import flags as _flags

        n_rows = 20000
        mc = await MiniCluster(tempfile.mkdtemp(prefix="ybtpu-ol-"),
                               num_tservers=1).start()
        conns = []
        try:
            c = mc.client()
            await c.create_table(usertable_info(), num_tablets=1,
                                 replication_factor=1)
            await mc.wait_for_leaders("usertable")
            rows = [{"ycsb_key": i,
                     **{f"field{j}": "x" * 100 for j in range(10)}}
                    for i in range(n_rows)]
            for i in range(0, n_rows, 2000):
                await c.insert("usertable", rows[i:i + 2000])
            ct = await c._table("usertable")
            loc = ct.locations[0]
            addr = loc.leader_addr()
            # flush: scans measure the columnar/pushdown path (the
            # steady state), not a 20k-row memtable decode per query
            await c.messenger.call(addr, "tserver", "flush",
                                   {"tablet_id": loc.tablet_id},
                                   timeout=30.0)
            # 64 distinct connections (a fleet of clients, not one
            # pipelined socket): per-connection inflight caps cannot
            # compose into a global bound across a fleet — holding
            # latency here is exactly the scheduler's job
            conns = [Messenger(f"ol-{i}") for i in range(64)]
            rng = np.random.default_rng(2)

            def payload():
                return {"tablet_id": loc.tablet_id,
                        "req": read_request_to_wire(ReadRequest(
                            ct.info.table_id,
                            pk_eq={"ycsb_key":
                                   int(rng.integers(0, n_rows))}))}

            from yugabyte_db_tpu.ops.scan import AggSpec

            def scan_payload():
                # one fixed aggregate signature: under load every
                # queued copy coalesces into ONE kernel launch
                return {"tablet_id": loc.tablet_id,
                        "req": read_request_to_wire(ReadRequest(
                            ct.info.table_id,
                            aggregates=(AggSpec("count"),)))}

            async def closed_loop(dur, workers=64, pl=payload):
                stop = time.perf_counter() + dur
                count = 0

                async def w(i):
                    nonlocal count
                    m = conns[i % len(conns)]
                    while time.perf_counter() < stop:
                        await m.call(addr, "tserver", "read", pl(),
                                     timeout=30.0)
                        count += 1
                await asyncio.gather(*[w(i) for i in range(workers)])
                return count / dur

            async def open_loop(rate, dur, deadline_s=2.0, pl=payload):
                """Open loop at `rate` for `dur` seconds.  Every op
                carries a realistic client DEADLINE: a completion past
                it is wasted server work the client no longer wants —
                achieved ops/s counts in-SLA completions only (the
                goodput an overloaded server actually delivers)."""
                lat, tasks = [], []
                shed = timed_out = conn_reset = 0
                retry_after = []

                async def one(i):
                    nonlocal shed, timed_out, conn_reset
                    m = conns[i % len(conns)]
                    t0 = time.perf_counter()
                    try:
                        await m.call(addr, "tserver", "read", pl(),
                                     timeout=deadline_s)
                        lat.append(time.perf_counter() - t0)
                    except asyncio.TimeoutError:
                        timed_out += 1
                    except RpcError as e:
                        if e.code == "SERVICE_UNAVAILABLE":
                            shed += 1
                            if e.retry_after_ms and len(retry_after) < 64:
                                retry_after.append(e.retry_after_ms)
                        elif e.code == "NETWORK_ERROR":
                            # a sibling op's deadline evicted this conn
                            # mid-flight — an overload casualty too
                            conn_reset += 1
                        else:
                            raise
                total = int(rate * dur)
                interval = 1.0 / rate
                t_start = time.perf_counter()
                for i in range(total):
                    due = t_start + i * interval
                    now = time.perf_counter()
                    if now < due:
                        await asyncio.sleep(due - now)
                    tasks.append(asyncio.ensure_future(one(i)))
                await asyncio.gather(*tasks)
                wall = time.perf_counter() - t_start
                lat_ms = sorted(x * 1e3 for x in lat)

                def pct(q):
                    if not lat_ms:
                        return 0.0
                    return lat_ms[min(len(lat_ms) - 1,
                                      int(q * len(lat_ms)))]
                return {"offered_ops_per_s": round(rate, 1),
                        "achieved_ops_per_s": round(len(lat) / wall, 1),
                        "ok": len(lat), "shed": shed,
                        "timed_out": timed_out, "conn_reset": conn_reset,
                        "deadline_s": deadline_s,
                        "shed_rate": round(shed / max(1, total), 3),
                        "retry_after_ms_seen": (
                            [min(retry_after), max(retry_after)]
                            if retry_after else None),
                        "p50_ms": round(pct(0.5), 2),
                        "p99_ms": round(pct(0.99), 2)}

            async def paired_overload(pl, sat):
                # PAIRED, interleaved rounds (the Q6/compaction
                # discipline): ON and OFF run back-to-back inside each
                # round so co-tenant noise hits both sides of a round
                # equally; keep each side's best-achieved run, ratio
                # from those
                on_rounds, off_rounds = [], []
                for _ in range(2):
                    on_rounds.append(
                        await open_loop(2 * sat, duration, pl=pl))
                    _flags.set_flag("scheduler_enabled", False)
                    try:
                        off_rounds.append(
                            await open_loop(2 * sat, duration, pl=pl))
                    finally:
                        _flags.set_flag("scheduler_enabled", True)
                on = max(on_rounds,
                         key=lambda r: r["achieved_ops_per_s"])
                off = max(off_rounds,
                          key=lambda r: r["achieved_ops_per_s"])
                return {"saturation_ops_per_s": round(sat, 1),
                        "scheduler_on": on, "scheduler_off": off,
                        "p99_ratio_rounds": [
                            round(a["p99_ms"] / max(b["p99_ms"], 1e-9), 3)
                            for a, b in zip(on_rounds, off_rounds)],
                        "p99_ratio_on_vs_off": round(
                            on["p99_ms"] / max(off["p99_ms"], 1e-9), 3),
                        "achieved_ratio_on_vs_off": round(
                            on["achieved_ops_per_s"]
                            / max(off["achieved_ops_per_s"], 1e-9), 3)}

            await closed_loop(0.5)                    # warm
            sat = await closed_loop(1.5)
            points = await paired_overload(payload, sat)
            # scan lane: same-signature aggregates coalesce into ONE
            # kernel launch per batch — under overload the scheduler
            # turns N queued copies into one engine execution, a real
            # capacity multiplier (the accelerator-boundary batching
            # the subsystem exists for)
            await closed_loop(0.5, pl=scan_payload)   # warm/compile
            scan_sat = await closed_loop(1.5, pl=scan_payload)
            scans = await paired_overload(scan_payload, scan_sat)
            return {"point_reads": points, "agg_scans": scans}
        finally:
            for m in conns:
                await m.shutdown()
            await mc.shutdown()

    try:
        return asyncio.run(run())
    except Exception as e:   # noqa: BLE001 — report, don't fail bench
        return {"error": str(e)[:200]}


def cluster_overload_bench():
    """Live fire on a REAL multi-process cluster (cluster/): 1 master +
    3 tservers + 1 open-loop driver, every one its own OS process with
    its own event loop and GIL — the shape the single-loop benches
    above cannot measure.  Four legs, one cluster:

    (a) scheduler ON vs OFF at 2x the measured saturation (paired
        rounds; the PR-3 separation without the shared-loop noise),
    (b) SLA-bounded goodput THROUGH a live tablet auto-split plus a
        blacklist-drain rebalance (balancer replica moves = the
        remote-bootstrap catch-up path) while the driver keeps firing
        (`split_goodput_ratio` vs the calm scheduler-ON round),
    (c) a seeded chaos round — SIGKILL a peer + stall a disk mid-load,
        restart with backoff — followed by a quiesced byte-verify of
        EVERY acked write (`chaos_missing`/`chaos_mismatched` WARN on
        any nonzero: acked data may never vanish),
    (d) bypass aggregate scans served by a SEPARATE replica process
        (rpc_bypass_scan) under the same point-write fire:
        `cluster_bypass_p95_impact` (the WARN gate — round p99s are
        spike-dominated on 2 cores, p95 medians hold steady) is the
        write-lane tail with scans / without — compare to the
        single-loop `bypass_p99_impact` (ROADMAP: separate-process
        bypass should approach 1.0).

    BENCH_CLUSTER_S bounds each phase (0 skips); BENCH_CHAOS_SEED
    replays a chaos round bit-for-bit."""
    import asyncio

    duration = float(os.environ.get("BENCH_CLUSTER_S", "2.5"))
    if duration <= 0:
        return None
    seed = int(os.environ.get("BENCH_CHAOS_SEED", "42"))

    async def run():
        from yugabyte_db_tpu.cluster import (ChaosController,
                                             ClusterSupervisor)
        from yugabyte_db_tpu.docdb.operations import ReadRequest
        from yugabyte_db_tpu.docdb.wire import read_request_to_wire
        from yugabyte_db_tpu.ops.scan import AggSpec

        sup = await ClusterSupervisor(
            tempfile.mkdtemp(prefix="ybtpu-cluster-"),
            num_tservers=3).start()
        out = {"processes": len(sup.procs) + 2}   # + driver + this one
        try:
            await sup.spawn_driver("drv-0")
            setup = await sup.call(
                "drv-0", "driver", "setup",
                {"rows": 2000, "num_tablets": 2,
                 "replication_factor": 2}, timeout=120.0)
            table_id = setup["table_id"]
            sat = (await sup.call(
                "drv-0", "driver", "saturation",
                {"seconds": 1.5, "workers": 32}, timeout=60.0)
            )["ops_per_s"]
            # cap the offered rate: the open loop materializes one task
            # per op and this box is 2 cores
            rate = min(2.0 * sat, 4000.0)
            out["saturation_ops_per_s"] = round(sat, 1)
            out["offered_ops_per_s"] = round(rate, 1)

            async def phase(tag, seconds=None, rate_=None, wf=1.0):
                return await sup.call(
                    "drv-0", "driver", "run_phase",
                    {"rate": rate_ or rate,
                     "seconds": seconds or duration,
                     "write_fraction": wf,
                     "sla_ms": 2000, "tag": tag}, timeout=180.0)

            # (a) scheduler ON/OFF, paired interleaved rounds ----------
            # mixed 50/50 read/write at 2x saturation: point-read
            # fusion + write group commit are where the scheduler's
            # micro-batching pays, and the separation is measured from
            # REMOTE processes (the shape the single-loop ycsb_overload
            # bench could not isolate)
            on_rounds, off_rounds = [], []
            for i in range(2):
                on_rounds.append(await phase(f"on{i}", wf=0.5))
                await sup.set_flag_all("scheduler_enabled", False,
                                       roles=("tserver",))
                try:
                    off_rounds.append(await phase(f"off{i}", wf=0.5))
                finally:
                    await sup.set_flag_all("scheduler_enabled", True,
                                           roles=("tserver",))
            on = max(on_rounds, key=lambda r: r["achieved_ops_per_s"])
            off = max(off_rounds, key=lambda r: r["achieved_ops_per_s"])
            out["scheduler"] = {
                "on": on, "off": off,
                "p99_ratio_rounds": [
                    round(a["p99_ms"] / max(b["p99_ms"], 1e-9), 3)
                    for a, b in zip(on_rounds, off_rounds)],
                # own keys (and thresholds): the single-loop block's
                # p99_ratio_on_vs_off threshold (0.5) is calibrated
                # for in-process dispatch; across real processes the
                # driver-side p99 includes client backoff+retries, so
                # the bar is "ON is not worse" at matched goodput
                "cluster_p99_on_vs_off": round(
                    on["p99_ms"] / max(off["p99_ms"], 1e-9), 3),
                "cluster_achieved_on_vs_off": round(
                    on["achieved_ops_per_s"]
                    / max(off["achieved_ops_per_s"], 1e-9), 3)}

            # (a2) write-path fusion levers ON/OFF, paired ------------
            # pure-write rounds at 2x (the write path dominates):
            # async flush (no apply-thread SST stall), fused consensus
            # appends (one fsync + one round per coalesced batch) and
            # cross-tablet dispatch fusion flipped together — the
            # PR-11 claim that `cluster_achieved_on_vs_off` ~1.0 was
            # unclaimed fusion, now measured as its own paired leg
            fusion_flags = ("async_flush_enabled",
                            "fused_replicate_enabled",
                            "sched_cross_tablet_fusion")
            # cool down leg (a)'s 2x backlog first (same reason leg
            # (b) settles), then force REAL flush traffic: at the
            # default 64MB threshold a short round never flushes and
            # the async-flush lever would measure nothing — 1MB makes
            # each round pay several memtable flushes, ON as frozen
            # handoffs to the flush executor, OFF as inline
            # apply-thread stalls (the ~20x p99 source)
            await asyncio.sleep(duration)
            await phase("fuse-settle", wf=1.0)
            await sup.set_flag_all("memstore_flush_threshold_bytes",
                                   1_000_000, roles=("tserver",))
            fon_rounds, foff_rounds = [], []
            try:
                for i in range(2):
                    fon_rounds.append(await phase(f"fuse-on{i}",
                                                  wf=1.0))
                    for fl in fusion_flags:
                        await sup.set_flag_all(fl, False,
                                               roles=("tserver",))
                    try:
                        foff_rounds.append(await phase(f"fuse-off{i}",
                                                       wf=1.0))
                    finally:
                        for fl in fusion_flags:
                            await sup.set_flag_all(fl, True,
                                                   roles=("tserver",))
            finally:
                await sup.set_flag_all("memstore_flush_threshold_bytes",
                                       64 * 1024 * 1024,
                                       roles=("tserver",))
            fon = max(fon_rounds,
                      key=lambda r: r["achieved_ops_per_s"])
            foff = max(foff_rounds,
                       key=lambda r: r["achieved_ops_per_s"])
            # flush/fusion counters from the live servers: the
            # counter-assert that handoffs actually happened and
            # coalesced groups rode fused appends
            fuse_counters = {"flush_stalls_avoided": 0,
                             "fused_appends": 0,
                             "fused_append_fanin_mean": []}
            for name in sup.tserver_names():
                if not sup.procs[name].alive():
                    continue
                snap = await sup.call(name, "tserver",
                                      "metrics_snapshot", {},
                                      timeout=10.0)
                for ent in snap.get("entities", []):
                    for mname, v in ent.get("metrics", {}).items():
                        if mname == "flush_stalls_avoided":
                            fuse_counters["flush_stalls_avoided"] += v
                        elif mname == "fused_appends":
                            fuse_counters["fused_appends"] += v
                        elif mname == "fused_append_fanin" and \
                                isinstance(v, dict) and v.get("count"):
                            fuse_counters[
                                "fused_append_fanin_mean"].append(
                                    v.get("mean_us", 0.0))
            fm = fuse_counters["fused_append_fanin_mean"]
            fuse_counters["fused_append_fanin_mean"] = (
                round(sum(fm) / len(fm), 2) if fm else None)
            out["write_fusion"] = {
                "on": fon, "off": foff,
                "counters": fuse_counters,
                "cluster_fused_p99_on_vs_off": round(
                    fon["p99_ms"] / max(foff["p99_ms"], 1e-9), 3),
                "cluster_fused_achieved_on_vs_off": round(
                    fon["achieved_ops_per_s"]
                    / max(foff["achieved_ops_per_s"], 1e-9), 3)}

            # (b) goodput through live split + rebalance ---------------
            # the control-plane legs run at 1x saturation, not 2x: the
            # question is what a SUSTAINABLE load loses to a live
            # split+rebalance, not how overload shed composes with it.
            # Cool down first — leg (a)'s 2x rounds leave a server-side
            # backlog that would zero the calm reference's goodput
            await asyncio.sleep(duration)
            await phase("settle", rate_=min(sat, 3000.0))
            calm = await phase("calm", rate_=min(sat, 3000.0))
            await sup.call("master-0", "master", "set_flag",
                           {"name": "tablet_split_size_threshold_bytes",
                            "value": 120_000}, timeout=10.0)
            await sup.call("master-0", "master", "set_flag",
                           {"name": "enable_automatic_tablet_splitting",
                            "value": True}, timeout=10.0)
            await sup.spawn_tserver(3)
            await sup.wait_tservers_live()
            await sup.call("master-0", "master", "blacklist",
                           {"ts_uuid": "ts-0"}, timeout=10.0)
            cp_phases, lb_actions = [], []
            split_fired = drained = False
            deadline = time.monotonic() + max(45.0, 18 * duration)
            while time.monotonic() < deadline:
                cp_phases.append(await phase("cp",
                                             rate_=min(sat, 3000.0)))
                for _ in range(2):   # each tick = at most one move
                    r = await sup.call("master-0", "master",
                                       "balance_tick", {}, timeout=15.0)
                    if r.get("action"):
                        lb_actions.append(r["action"])
                snap = await sup.call("master-0", "master",
                                      "metrics_snapshot", {},
                                      timeout=10.0)
                if not split_fired and \
                        len(snap["tablet_reports"]) > 2:
                    split_fired = True
                    # one live split is the measurement; stop the
                    # splitter so the drain chases a FIXED replica set
                    # instead of freshly split children forever
                    await sup.call(
                        "master-0", "master", "set_flag",
                        {"name": "enable_automatic_tablet_splitting",
                         "value": False}, timeout=10.0)
                ts0 = await sup.call("ts-0", "tserver",
                                     "metrics_snapshot", {},
                                     timeout=10.0)
                drained = not ts0["tablets"]
                if split_fired and drained:
                    break
            await sup.call("master-0", "master", "set_flag",
                           {"name": "enable_automatic_tablet_splitting",
                            "value": False}, timeout=10.0)
            worst = min(cp_phases, key=lambda r: r["achieved_ops_per_s"])
            mean_ach = (sum(p["achieved_ops_per_s"] for p in cp_phases)
                        / len(cp_phases))
            out["split_rebalance"] = {
                "split_fired": split_fired,
                "ts0_drained": drained,
                "balancer_actions": lb_actions[:8],
                "phases": len(cp_phases),
                "calm_1x": calm,
                "worst_phase": worst,
                "mean_achieved_ops_per_s": round(mean_ach, 1),
                # SLA-bounded goodput through the convulsion, vs the
                # calm 1x round on the same cluster
                "split_goodput_ratio": round(
                    mean_ach
                    / max(calm["achieved_ops_per_s"], 1e-9), 3)}

            # (c) seeded chaos round + quiesced byte-verify ------------
            chaos = ChaosController(sup, seed=seed)
            plan = chaos.plan_round(kills=1, stalls=1, stall_s=1.0,
                                    round_s=duration, spare=("ts-0",))
            load = asyncio.ensure_future(
                phase("chaos", seconds=duration + 2.0))
            try:
                log = await chaos.run_round(plan)
                chaos_phase = await load
            finally:
                if not load.done():   # run_round raised: reap the
                    load.cancel()     # driver phase before teardown
                    try:
                        await load
                    except (Exception, asyncio.CancelledError):
                        pass
            await chaos.clear_all()
            verify = await sup.call("drv-0", "driver", "verify", {},
                                    timeout=600.0)
            out["chaos"] = {"seed": seed,
                            "plan": [list(e.as_tuple()) for e in plan],
                            "executed": [list(x) for x in log],
                            "phase": chaos_phase, "verify": verify}
            out["chaos_missing"] = verify["missing"]
            out["chaos_mismatched"] = verify["mismatched"]
            out["chaos_unreachable"] = verify["unreachable"]

            # (d) bypass from a SEPARATE replica process ---------------
            # the single-loop bypass_scan bench's shape, with real
            # process isolation: point writes fire at usertable while
            # aggregate scans hit a SEPARATE analytics table (written
            # once, flushed — the keyless scanner needs clean runs)
            # served via rpc_bypass_scan by a follower tserver process
            from yugabyte_db_tpu.docdb.table_codec import TableInfo
            from yugabyte_db_tpu.dockv.packed_row import (
                ColumnSchema, ColumnType, TableSchema)
            from yugabyte_db_tpu.dockv.partition import PartitionSchema
            ainfo = TableInfo("", "analytics", TableSchema(columns=(
                ColumnSchema(0, "k", ColumnType.INT64,
                             is_hash_key=True),
                ColumnSchema(1, "v", ColumnType.FLOAT64)), version=1),
                PartitionSchema("hash", 1))
            c = sup.client()
            try:
                await c.create_table(ainfo, num_tablets=1,
                                     replication_factor=2)
                n_a = 10_000
                for lo in range(0, n_a, 2000):
                    await c.insert("analytics", [
                        {"k": i, "v": float(i)}
                        for i in range(lo, lo + 2000)])
                act = await c._table("analytics", refresh=True)
                for loc in act.locations:
                    await c.messenger.call(
                        loc.leader_addr(), "tserver", "flush",
                        {"tablet_id": loc.tablet_id}, timeout=30.0)
                a_table_id = act.info.table_id
                leaders = {loc.leader for loc in act.locations}
            finally:
                await c.messenger.shutdown()
            # scan from a process that leads NONE of the analytics
            # tablets — the purest "analytics replica" (its store holds
            # follower-applied rows; the pinner's safe-time wait plus a
            # local flush give it a clean snapshot)
            victim = None
            for name in sup.tserver_names():
                if not sup.procs[name].alive():
                    continue
                snap = await sup.call(name, "tserver",
                                      "metrics_snapshot", {},
                                      timeout=10.0)
                mine = {t: d for t, d in snap["tablets"].items()
                        if t.startswith(a_table_id)}
                if mine and snap["uuid"] not in leaders:
                    victim = name
                    break
                if mine and victim is None:
                    victim = name          # fallback: any replica host
            await sup.call(victim, "tserver", "set_flag",
                           {"name": "bypass_reader_enabled",
                            "value": True}, timeout=10.0)
            agg_req = read_request_to_wire(ReadRequest(
                a_table_id, aggregates=(AggSpec("count"),
                                        AggSpec("sum", ("col", 1)))))
            byp_req = {"table_id": a_table_id, "req": agg_req}
            # the same aggregate THROUGH the hot path: an ordinary
            # `read` RPC at the analytics leader (the contrast round)
            lloc = act.locations[0]
            rpc_req = {"tablet_id": lloc.tablet_id, "req": agg_req}
            leader_name = victim
            for n in sup.tserver_names():
                if not sup.procs[n].alive():
                    continue
                u = (await sup.call(n, "tserver", "metrics_snapshot",
                                    {}, timeout=10.0))["uuid"]
                if u == lloc.leader:
                    leader_name = n
                    break
            # writes at 1x saturation: the isolation question is what
            # analytics traffic does to a HEALTHY write lane (at 2x
            # the p99 already sits at the SLA ceiling and the ratio
            # saturates); scans are PACED — an analytics session, not
            # a scan storm, so the ratio measures loop/GIL coupling
            # rather than raw 2-core oversubscription.  Re-probe
            # saturation first: the cluster behind it (split children,
            # moved replicas, restarted peers) is not the one the
            # opening probe measured
            sat2 = (await sup.call(
                "drv-0", "driver", "saturation",
                {"seconds": 1.0, "workers": 32}, timeout=60.0)
            )["ops_per_s"]
            byp_rate = min(sat2, 3000.0)
            out["post_chaos_saturation_ops_per_s"] = round(sat2, 1)
            scan_every_s = 0.25
            # PINNED compile-warm rounds before anything measured
            # (ROADMAP write-path item (d)): the first bypass scan pays
            # the local follower flush + kernel compile, the first RPC
            # read its own scan-kernel compile, and the first write
            # phase the leaders' apply-path warmup.  A single kernel
            # compile landing inside one 3s measured round swung that
            # round's p99 several-fold on this box and tripped the
            # cluster_p99_spread <= 3x WARN; with all three warmed, the
            # spread gate measures the engine, not XLA.
            await sup.call(victim, "tserver", "bypass_scan", byp_req,
                           timeout=60.0)
            await sup.call(leader_name, "tserver", "read", rpc_req,
                           timeout=60.0)
            await phase("compile_warm", rate_=byp_rate, seconds=1.0)

            async def scan_loop(stop_at, call, stats):
                while time.monotonic() < stop_at:
                    t0 = time.monotonic()
                    try:
                        r = await call()
                        stats["rounds"] += 1
                        stats["last"] = r.get("stats")
                    except Exception as e:   # noqa: BLE001 — the
                        # write-lane p99 is the metric; a scan refusal
                        # (e.g. a flush race) is counted, not fatal
                        stats["errors"] += 1
                        stats["last_error"] = str(e)[:120]
                    dt = time.monotonic() - t0
                    if dt < scan_every_s:
                        await asyncio.sleep(scan_every_s - dt)

            byp_dur = max(duration, 3.0)

            async def measured_round(tag, call, stats):
                # `stats` accumulates ACROSS rounds — the reported
                # scan counts must cover all 3, not just the last
                stop_at = time.monotonic() + byp_dur
                scans = asyncio.ensure_future(
                    scan_loop(stop_at, call, stats))
                try:
                    ph = await phase(tag, rate_=byp_rate,
                                     seconds=byp_dur)
                    await scans
                finally:
                    if not scans.done():   # phase raised: reap
                        scans.cancel()
                        try:
                            await scans
                        except (Exception, asyncio.CancelledError):
                            pass
                return ph

            def _byp_call():
                return sup.call(victim, "tserver", "bypass_scan",
                                byp_req, timeout=60.0)

            def _rpc_call():
                return sup.call(leader_name, "tserver", "read",
                                rpc_req, timeout=60.0)

            # paired interleaved rounds, MEDIAN per side: a flush
            # pause landing in one 3s window swings a single round's
            # p99 several-fold on this box, and best-of would let one
            # lucky round hide a real coupling
            def med(rounds, key):
                vals = sorted(r[key] for r in rounds)
                return vals[len(vals) // 2]

            # --- per-round ASH deltas (p99 attribution) ------------
            # every measured round brackets a tracez sweep of the live
            # tservers; the per-state CUMULATIVE tallies diff into a
            # wait-state delta for that round, so an over-spread p99
            # gets labeled with its dominant wait instead of being
            # "flush-pause luck" (cluster_p99_attribution below)
            from yugabyte_db_tpu.cluster.collector import (
                attribute_rounds, merge_ash_cumulative)

            async def ash_cum():
                dumps = []
                for nm in sup.tserver_names():
                    if not sup.procs[nm].alive():
                        continue
                    try:
                        dumps.append(await sup.call(
                            nm, "tserver", "tracez", {}, timeout=10.0))
                    except Exception:   # noqa: BLE001 — a draining
                        continue        # peer drops out of the diff
                return merge_ash_cumulative(dumps)

            attr_rounds = []

            async def attributed(tag, factory):
                pre = await ash_cum()
                r = await factory()
                post = await ash_cum()
                delta = {s: post.get(s, 0) - pre.get(s, 0)
                         for s in post
                         if post.get(s, 0) > pre.get(s, 0)}
                attr_rounds.append({"tag": tag, "p99_ms": r["p99_ms"],
                                    "wait_delta": delta})
                return r

            bases, byps, rpcs = [], [], []
            byp_stats = {"rounds": 0, "errors": 0, "last": None,
                         "last_error": None}
            rpc_stats = {"rounds": 0, "errors": 0, "last": None,
                         "last_error": None}
            for i in range(3):
                bases.append(await attributed(
                    f"bypbase{i}",
                    lambda i=i: phase(f"bypbase{i}", rate_=byp_rate,
                                      seconds=byp_dur)))
                byps.append(await attributed(
                    f"bypload{i}",
                    lambda i=i: measured_round(f"bypload{i}",
                                               _byp_call, byp_stats)))
                rpcs.append(await attributed(
                    f"rpcload{i}",
                    lambda i=i: measured_round(f"rpcload{i}",
                                               _rpc_call, rpc_stats)))
            out["bypass_from_replica"] = {
                "replica_process": victim,
                "leader_process": leader_name,
                "analytics_rows": n_a,
                "scan_every_s": scan_every_s,
                "rounds": 3,
                "bypass_scan_rounds": byp_stats["rounds"],
                "bypass_scan_errors": byp_stats["errors"],
                "scan_stats": byp_stats["last"],
                **({"scan_last_error": byp_stats["last_error"]}
                   if byp_stats["last_error"] else {}),
                "rpc_scan_rounds": rpc_stats["rounds"],
                "p99_ms_no_scan": med(bases, "p99_ms"),
                "p99_ms_with_bypass": med(byps, "p99_ms"),
                "p99_ms_with_rpc_scans": med(rpcs, "p99_ms"),
                "p99_ms_rounds": {
                    "base": [r["p99_ms"] for r in bases],
                    "bypass": [r["p99_ms"] for r in byps],
                    "rpc": [r["p99_ms"] for r in rpcs]},
                # max/median of each side's round p99s: flush-pause
                # luck swung this ~20x before async flush; the PR-11
                # acceptance bar is <= 3x (WARN-wired as
                # cluster_p99_spread — the worst side)
                "cluster_p99_spread": max(
                    round(max(vals) / max(sorted(vals)[len(vals) // 2],
                                          1e-9), 3)
                    for vals in ([r["p99_ms"] for r in bases],
                                 [r["p99_ms"] for r in byps],
                                 [r["p99_ms"] for r in rpcs])),
                "write_lane_no_scan": bases[-1],
                "write_lane_with_bypass": byps[-1],
                "write_lane_with_rpc_scans": rpcs[-1],
                # bypass from a real replica process vs the same
                # aggregate through the leader's hot path: the p99
                # impact ratios the ROADMAP bypass item (c) asks for
                # (medians across rounds; p95 twin recorded for the
                # noise floor on this 2-core box)
                "cluster_bypass_p99_impact": round(
                    med(byps, "p99_ms")
                    / max(med(bases, "p99_ms"), 1e-9), 3),
                "rpc_scan_p99_impact": round(
                    med(rpcs, "p99_ms")
                    / max(med(bases, "p99_ms"), 1e-9), 3),
                "cluster_bypass_p95_impact": round(
                    med(byps, "p95_ms")
                    / max(med(bases, "p95_ms"), 1e-9), 3),
                "rpc_scan_p95_impact": round(
                    med(rpcs, "p95_ms")
                    / max(med(bases, "p95_ms"), 1e-9), 3)}
            # every round whose p99 exceeds the 3x spread gate gets
            # its dominant wait state (flush/fsync/queue/compile/
            # lock/cpu) — the ISSUE 14 acceptance key
            out["cluster_p99_attribution"] = attribute_rounds(
                attr_rounds, spread_gate=3.0)
            return out
        finally:
            await sup.shutdown()

    try:
        return asyncio.run(run())
    except Exception as e:   # noqa: BLE001 — report, don't fail bench
        if os.environ.get("BENCH_DEBUG"):
            raise
        return {"error": str(e)[:300]}


def bypass_scan_bench():
    """Analytics bypass under live point-write fire: a 2x-saturation
    open-loop YCSB point-WRITE load rides the real RPC path while Q6
    aggregate scans run (a) through the tserver hot path and (b)
    through the SST-direct bypass engine from a plain worker thread.
    Reports both scan rates, the write-lane p99 with and without the
    bypass running (the isolation claim: bypass load must not queue on
    the event loop — `bypass_p99_impact` is WARN-wired), the keyless-
    scan counter, and the prefilter selectivity split.
    BENCH_BYPASS_S=0 skips."""
    import asyncio
    import threading

    duration = float(os.environ.get("BENCH_BYPASS_S", "2.5"))
    if duration <= 0:
        return None
    sf = float(os.environ.get("BENCH_BYPASS_SF", "0.05"))

    async def run():
        from yugabyte_db_tpu.bypass import BypassSession
        from yugabyte_db_tpu.docdb.operations import (
            ReadRequest, RowOp, WriteRequest)
        from yugabyte_db_tpu.docdb.wire import (
            read_request_to_wire, write_request_to_wire)
        from yugabyte_db_tpu.models.tpch import (
            TPCH_Q6, generate_lineitem, lineitem_range_info,
            numpy_reference)
        from yugabyte_db_tpu.models.ycsb import usertable_info
        from yugabyte_db_tpu.rpc.messenger import Messenger, RpcError
        from yugabyte_db_tpu.storage.columnar import KEY_REBUILD_STATS

        data = generate_lineitem(sf)
        n_li = len(data["rowid"])
        q6_ref = numpy_reference(TPCH_Q6, data)
        n_rows = 10000
        mc = await __import__(
            "yugabyte_db_tpu.tools.mini_cluster",
            fromlist=["MiniCluster"]).MiniCluster(
                tempfile.mkdtemp(prefix="ybtpu-byp-"),
                num_tservers=1).start()
        conns = []
        try:
            c = mc.client()
            await c.create_table(usertable_info(), num_tablets=1,
                                 replication_factor=1)
            await c.create_table(lineitem_range_info(), num_tablets=1,
                                 replication_factor=1)
            await mc.wait_for_leaders("usertable")
            await mc.wait_for_leaders("lineitem_r")
            await c.insert("usertable", [
                {"ycsb_key": i,
                 **{f"field{j}": "x" * 100 for j in range(10)}}
                for i in range(n_rows)])
            # the analytics shard: bulk-loaded straight into the peer's
            # tablet (the local-replica shape the bypass engine reads)
            ts = mc.tservers[0]
            li_peer = next(p for p in ts.peers.values()
                           if p.tablet.info.name == "lineitem_r")
            li_peer.tablet.bulk_load(data, block_rows=65536)
            uct = await c._table("usertable")
            uloc = uct.locations[0]
            lct = await c._table("lineitem_r")
            lloc = lct.locations[0]
            addr = uloc.leader_addr()
            conns = [Messenger(f"byp-{i}") for i in range(32)]
            rng = np.random.default_rng(3)

            def wr_payload():
                k = int(rng.integers(0, n_rows))
                return {"tablet_id": uloc.tablet_id,
                        "req": write_request_to_wire(WriteRequest(
                            uct.info.table_id, ops=[RowOp("upsert", {
                                "ycsb_key": k,
                                **{f"field{j}": "y" * 100
                                   for j in range(10)}})]))}

            scan_req = {"tablet_id": lloc.tablet_id,
                        "req": read_request_to_wire(ReadRequest(
                            lct.info.table_id, where=TPCH_Q6.where,
                            aggregates=TPCH_Q6.aggs))}

            async def write_closed(dur, workers=32):
                stop = time.perf_counter() + dur
                count = 0

                async def w(i):
                    nonlocal count
                    m = conns[i % len(conns)]
                    while time.perf_counter() < stop:
                        await m.call(addr, "tserver", "write",
                                     wr_payload(), timeout=30.0)
                        count += 1
                await asyncio.gather(*[w(i) for i in range(workers)])
                return count / dur

            async def write_open(rate, dur):
                lat, tasks = [], []
                dropped = 0

                async def one(i):
                    nonlocal dropped
                    m = conns[i % len(conns)]
                    t0 = time.perf_counter()
                    try:
                        await m.call(addr, "tserver", "write",
                                     wr_payload(), timeout=2.0)
                        lat.append(time.perf_counter() - t0)
                    except (asyncio.TimeoutError, RpcError, OSError):
                        dropped += 1
                total = int(rate * dur)
                interval = 1.0 / rate
                t_start = time.perf_counter()
                for i in range(total):
                    due = t_start + i * interval
                    now = time.perf_counter()
                    if now < due:
                        await asyncio.sleep(due - now)
                    tasks.append(asyncio.ensure_future(one(i)))
                await asyncio.gather(*tasks)
                lat_ms = sorted(x * 1e3 for x in lat)

                def pct(q):
                    if not lat_ms:
                        return 0.0
                    return lat_ms[min(len(lat_ms) - 1,
                                      int(q * len(lat_ms)))]
                return {"achieved_ops_per_s": round(
                            len(lat) / max(dur, 1e-9), 1),
                        "dropped": dropped,
                        "p50_ms": round(pct(0.5), 2),
                        "p99_ms": round(pct(0.99), 2)}

            async def rpc_scans_under_load(rate, dur):
                """Q6 RPCs through the tserver while the write load
                runs: the hot-path scan rate the bypass is measured
                against."""
                done = {"scans": 0}

                async def scanner():
                    m = conns[0]
                    stop = time.perf_counter() + dur
                    while time.perf_counter() < stop:
                        await m.call(addr, "tserver", "read", scan_req,
                                     timeout=30.0)
                        done["scans"] += 1
                wr_task = asyncio.ensure_future(write_open(rate, dur))
                await scanner()
                wr = await wr_task
                return done["scans"], wr

            def bypass_loop(dur, out):
                # a parity failure here must surface as THE bench
                # error, not launder into a zero-throughput number
                try:
                    t_end = time.perf_counter() + dur
                    scans = 0
                    # the peer form: pin waits on MVCC safe time,
                    # exactly what a consensus-served shard requires
                    with BypassSession([li_peer]) as s:
                        while time.perf_counter() < t_end:
                            outs, _cnt, st = s.scan_aggregate(
                                TPCH_Q6.where, TPCH_Q6.aggs, None)
                            rel = abs(float(outs[0]) - q6_ref) \
                                / max(abs(q6_ref), 1e-9)
                            assert rel < 1e-5, \
                                f"bypass q6 mismatch {rel}"
                            scans += 1
                        out.update(scans=scans, stats=st,
                                   session=s.stats())
                except BaseException as e:   # noqa: BLE001 — re-raised
                    out["error"] = repr(e)   # by the caller

            # warm both paths (compiles) before any timed round
            await conns[0].call(addr, "tserver", "read", scan_req,
                                timeout=60.0)
            warm = {}
            bypass_loop(0.1, warm)
            sat = await write_closed(1.0)
            rate = 2 * sat
            # round A: write load alone (the p99 baseline)
            alone = await write_open(rate, duration)
            # round B: write load + hot-path RPC scans
            rpc_scans, wr_rpc = await rpc_scans_under_load(rate, duration)
            # round C: write load + bypass scans on a worker thread
            bp_out = {}
            r0 = KEY_REBUILD_STATS["rebuilds"]
            th = threading.Thread(target=bypass_loop,
                                  args=(duration, bp_out))
            th.start()
            with_bp = await write_open(rate, duration)
            th.join(60)
            if "error" in bp_out:
                raise RuntimeError(
                    f"bypass scan thread failed: {bp_out['error']}")
            st = bp_out.get("stats", {})
            sess = bp_out.get("session", {})
            pf_in = st.get("prefilter_rows_in", 0)
            pf_kept = st.get("prefilter_rows_kept", 0)
            return {
                "lineitem_rows": n_li,
                "write_saturation_ops_per_s": round(sat, 1),
                "offered_write_ops_per_s": round(rate, 1),
                "write_alone": alone,
                "write_with_rpc_scans": wr_rpc,
                "write_with_bypass": with_bp,
                "hotpath_scan_rows_per_s": round(
                    rpc_scans * n_li / duration, 1),
                "bypass_scan_rows_per_s": round(
                    bp_out.get("scans", 0) * n_li / duration, 1),
                "bypass_vs_hotpath": round(
                    bp_out.get("scans", 0) / max(rpc_scans, 1e-9), 3),
                "bypass_p99_impact": round(
                    with_bp["p99_ms"] / max(alone["p99_ms"], 1e-9), 3),
                "keyless_blocks": sess.get("keyless_blocks"),
                "blocks": sess.get("blocks"),
                "key_rebuilds": KEY_REBUILD_STATS["rebuilds"] - r0,
                "prefilter_selectivity": round(
                    pf_kept / max(pf_in, 1), 4) if pf_in else None,
                "prefilter_rows_in": pf_in,
                "prefilter_rows_kept": pf_kept,
            }
        finally:
            for m in conns:
                await m.shutdown()
            await mc.shutdown()

    try:
        return asyncio.run(run())
    except Exception as e:   # noqa: BLE001 — report, don't fail bench
        return {"error": str(e)[:200]}


def matview_bench():
    """Incremental materialized views under live write fire (matview/):
    N registered GROUP BY views fold the CDC stream while a
    2x-saturation open-loop point-write load rides the RPC path.
    Reports the view-staleness p50/p99 sampled through the round, the
    write-lane p99 with and without the maintainers running
    (`matview_p99_impact` — informational, the maintainers share the
    client event loop), and the headline `matview_vs_rescan` ratio:
    serving the freshest answer from the maintained partials vs
    re-answering the same GROUP BY with a full grouped rescan per
    read — WARN-wired, incremental must WIN (> 1).
    BENCH_MATVIEW_S bounds the round (0 skips); BENCH_MATVIEW_ROWS
    sizes the base table; BENCH_MATVIEW_VIEWS sets N."""
    import asyncio

    duration = float(os.environ.get("BENCH_MATVIEW_S", "2.5"))
    if duration <= 0:
        return None
    n_rows = int(os.environ.get("BENCH_MATVIEW_ROWS", "20000"))
    n_views = int(os.environ.get("BENCH_MATVIEW_VIEWS", "3"))
    n_groups = 16

    async def run():
        from yugabyte_db_tpu.docdb.operations import (
            ReadRequest, RowOp, WriteRequest)
        from yugabyte_db_tpu.docdb.table_codec import TableInfo
        from yugabyte_db_tpu.docdb.wire import write_request_to_wire
        from yugabyte_db_tpu.dockv.packed_row import (
            ColumnSchema, ColumnType, TableSchema)
        from yugabyte_db_tpu.dockv.partition import PartitionSchema
        from yugabyte_db_tpu.matview import ViewDef
        from yugabyte_db_tpu.ops.scan import AggSpec, HashGroupSpec
        from yugabyte_db_tpu.rpc.messenger import Messenger, RpcError
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

        schema = TableSchema(columns=(
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "g", ColumnType.INT64),
            ColumnSchema(2, "v", ColumnType.INT64),
        ), version=1)
        info = TableInfo("", "kv", schema, PartitionSchema("hash", 1))
        mc = await MiniCluster(tempfile.mkdtemp(prefix="ybtpu-mv-"),
                               num_tservers=1).start()
        conns = []
        try:
            c = mc.client()
            await c.create_table(info, num_tablets=1,
                                 replication_factor=1)
            await mc.wait_for_leaders("kv")
            rng = np.random.default_rng(11)
            for lo in range(0, n_rows, 2000):
                await c.insert("kv", [
                    {"k": i, "g": i % n_groups,
                     "v": int(rng.integers(0, 1 << 20))}
                    for i in range(lo, min(lo + 2000, n_rows))])

            # N views over the same stream: plain partials, MIN/MAX
            # (the retraction/re-scan path), and a filtered slice
            defs = [
                ViewDef("mv_sum", "kv", "", ["g"],
                        [("count", None, "cnt"),
                         ("sum", ("col", "v"), "total")]),
                ViewDef("mv_mm", "kv", "", ["g"],
                        [("min", ("col", "v"), "lo"),
                         ("max", ("col", "v"), "hi")]),
                ViewDef("mv_flt", "kv", "", ["g"],
                        [("count", None, "cnt"),
                         ("sum", ("col", "v"), "total")],
                        where=("cmp", "ge", ("col", "v"),
                               ("const", 1 << 19))),
            ][:n_views]
            mts = [await c.matviews().create(vd) for vd in defs]

            ct = await c._table("kv")
            loc = ct.locations[0]
            addr = loc.leader_addr()
            conns = [Messenger(f"mv-{i}") for i in range(32)]

            def wr_payload():
                k = int(rng.integers(0, n_rows))   # updates: retraction
                return {"tablet_id": loc.tablet_id,
                        "req": write_request_to_wire(WriteRequest(
                            ct.info.table_id, ops=[RowOp("upsert", {
                                "k": k, "g": k % n_groups,
                                "v": int(rng.integers(0, 1 << 20))})]))}

            async def write_closed(dur, workers=32):
                stop = time.perf_counter() + dur
                done = [0]

                async def w(i):
                    m = conns[i % len(conns)]
                    while time.perf_counter() < stop:
                        try:
                            await m.call(addr, "tserver", "write",
                                         wr_payload(), timeout=2.0)
                            done[0] += 1
                        except (asyncio.TimeoutError, RpcError, OSError):
                            pass
                await asyncio.gather(*[w(i) for i in range(workers)])
                return done[0] / max(dur, 1e-9)

            async def write_open(rate, dur, sample_staleness=False):
                lat, tasks, staleness = [], [], []
                dropped = 0

                async def one(i):
                    nonlocal dropped
                    m = conns[i % len(conns)]
                    t0 = time.perf_counter()
                    try:
                        await m.call(addr, "tserver", "write",
                                     wr_payload(), timeout=2.0)
                        lat.append(time.perf_counter() - t0)
                    except (asyncio.TimeoutError, RpcError, OSError):
                        dropped += 1
                total = int(rate * dur)
                interval = 1.0 / rate
                t_start = time.perf_counter()
                for i in range(total):
                    due = t_start + i * interval
                    now = time.perf_counter()
                    if now < due:
                        await asyncio.sleep(due - now)
                    if sample_staleness and i % 25 == 0:
                        staleness.extend(mt.staleness_ms()
                                         for mt in mts)
                    tasks.append(asyncio.ensure_future(one(i)))
                await asyncio.gather(*tasks)
                lat_ms = sorted(x * 1e3 for x in lat)

                def pct(vals, q):
                    if not vals:
                        return 0.0
                    vals = sorted(vals)
                    return vals[min(len(vals) - 1, int(q * len(vals)))]
                out = {"achieved_ops_per_s": round(
                           len(lat) / max(dur, 1e-9), 1),
                       "dropped": dropped,
                       "p50_ms": round(pct(lat_ms, 0.5), 2),
                       "p99_ms": round(pct(lat_ms, 0.99), 2)}
                if sample_staleness:
                    finite = [s for s in staleness
                              if s != float("inf")]
                    out["staleness_p50_ms"] = round(
                        pct(finite, 0.5), 2)
                    out["staleness_p99_ms"] = round(
                        pct(finite, 0.99), 2)
                return out

            sat = await write_closed(1.0)
            rate = 2 * sat
            # round A: maintainers quiesced — the write-p99 baseline
            for mt in mts:
                await mt.stop()
            alone = await write_open(rate, duration)
            # round B: maintainers folding live
            for mt in mts:
                mt.start()
            with_mv = await write_open(rate, duration,
                                       sample_staleness=True)

            # incremental serve vs repeated full grouped rescan: the
            # view answers at its watermark after folding ONE delta;
            # the rescan re-answers the identical GROUP BY from scratch
            vd0, mt0 = defs[0], mts[0]
            for mt in mts[1:]:
                await mt.stop()          # isolate the measured view
            gspec = HashGroupSpec(cols=(1,))
            aggs = (AggSpec("count"), AggSpec("sum", ("col", 2)))
            reads = int(os.environ.get("BENCH_MATVIEW_READS", "15"))
            # drain round B's fold backlog first: the measured reads
            # time the steady state (fold ONE delta, serve), not the
            # overload recovery
            await c.matviews().read_rows(vd0.name, max_staleness_ms=0.0)
            t0 = time.perf_counter()
            for _ in range(reads):
                await conns[0].call(addr, "tserver", "write",
                                    wr_payload(), timeout=2.0)
                await c.matviews().read_rows(
                    vd0.name, max_staleness_ms=0.0)
            t_inc = time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(reads):
                await conns[0].call(addr, "tserver", "write",
                                    wr_payload(), timeout=2.0)
                await c.scan("kv", ReadRequest(
                    "", aggregates=aggs, group_by=gspec))
            t_rescan = time.perf_counter() - t0

            stats = {vd.name: {k: st[k] for k in
                               ("txns_applied", "rows_added",
                                "rows_retracted", "minmax_rescans",
                                "budget_exceeded", "full_rescans")}
                     for vd, st in ((vd, c.matviews().stats(vd.name))
                                    for vd in defs)}
            return {
                "views": len(defs), "base_rows": n_rows,
                "write_saturation_ops_per_s": round(sat, 1),
                "offered_write_ops_per_s": round(rate, 1),
                "write_alone": alone,
                "write_with_matviews": with_mv,
                "matview_p99_impact": round(
                    with_mv["p99_ms"] / max(alone["p99_ms"], 1e-9), 3),
                "staleness_p50_ms": with_mv.pop("staleness_p50_ms"),
                "staleness_p99_ms": with_mv.pop("staleness_p99_ms"),
                "incremental_read_ms": round(t_inc * 1e3 / reads, 2),
                "rescan_read_ms": round(t_rescan * 1e3 / reads, 2),
                "matview_vs_rescan": round(t_rescan / max(t_inc, 1e-9),
                                           3),
                "maintainer_stats": stats,
            }
        finally:
            try:
                await c.matviews().stop()
            except Exception:
                pass
            for m in conns:
                await m.shutdown()
            await mc.shutdown()

    try:
        return asyncio.run(run())
    except Exception as e:   # noqa: BLE001 — report, don't fail bench
        return {"error": str(e)[:200]}


def tpch_bypass_bench(data, repeats):
    """TPC-H Q1/Q6 routed through ``client.scan_bypass`` (ROADMAP
    bypass item (e)): the SAME lineitem rows served from a one-tserver
    mini cluster, each query measured over the RPC hot path
    (``client.scan``) and the SST-direct bypass engine back-to-back,
    so the headline q6/q1 blocks report a bypass column by default and
    ``bypass_vs_hotpath`` regression-WARNs like any other ratio.
    BENCH_TPCH_BYPASS=0 skips (the column then reads "skipped")."""
    import asyncio

    if os.environ.get("BENCH_TPCH_BYPASS", "1") == "0":
        return None

    async def run():
        from yugabyte_db_tpu.docdb.operations import ReadRequest
        from yugabyte_db_tpu.models.tpch import (
            TPCH_Q6, lineitem_range_info, lineitem_str_data,
            lineitem_str_info, numpy_reference, tpch_q1_str)
        from yugabyte_db_tpu.utils import flags

        n_li = len(data["rowid"])
        mc = await __import__(
            "yugabyte_db_tpu.tools.mini_cluster",
            fromlist=["MiniCluster"]).MiniCluster(
                tempfile.mkdtemp(prefix="ybtpu-tpchbp-"),
                num_tservers=1).start()
        try:
            c = mc.client()
            # q6 scans the numeric range-sharded clone; q1 scans the
            # STRING-keyed clone through the dict-grouped kernel, so
            # the bypass column exercises the group-keyed partial
            # combine (ops/scan.combine_grouped_partials) on BOTH the
            # hot-path client fan-out and the bypass session
            ts = mc.tservers[0]
            peers = {}
            for info, rows in ((lineitem_range_info(), data),
                               (lineitem_str_info(),
                                lineitem_str_data(data))):
                await c.create_table(info, num_tablets=1,
                                     replication_factor=1)
                await mc.wait_for_leaders(info.name)
                peer = next(p for p in ts.peers.values()
                            if p.tablet.info.name == info.name)
                peer.tablet.bulk_load(rows, block_rows=65536)
                peers[info.name] = peer
            c.set_bypass_provider(
                lambda table: [peers[table]] if table in peers
                else None)
            flags.set_flag("bypass_reader_enabled", True)
            out = {}
            rounds = max(2, repeats // 2)
            q1s = tpch_q1_str()
            for q, tab in ((TPCH_Q6, "lineitem_r"),
                           (q1s, "lineitem_s")):
                def req():
                    return ReadRequest("", where=q.where,
                                       aggregates=q.aggs,
                                       group_by=q.group)
                hot_warm = await c.scan(tab, req())
                byp_warm = await c.scan_bypass(tab, req())
                assert c.last_bypass["used"], (
                    f"{q.name}: bypass fell back "
                    f"({c.last_bypass['reason']})")
                # parity: q6 vs direct numpy; q1 bypass-vs-hotpath BY
                # GROUP KEY (slot order vs first-seen order differ; the
                # byte-level parity proof lives in tests/ — this guards
                # the BENCH wiring, and a mismatch must fail the bench)
                if q.name == "q6":
                    ref = numpy_reference(q, data)
                    got = float(byp_warm.agg_values[0])
                    assert abs(got - ref) / max(abs(ref), 1e-9) < 1e-5, \
                        f"bypass q6 mismatch: {got} vs {ref}"
                else:
                    def keyed(resp):
                        cnt = np.asarray(resp.group_counts)
                        return {
                            tuple(str(v[g]) for v in resp.group_values):
                            (int(cnt[g]),) + tuple(
                                float(np.asarray(v)[g])
                                for v in resp.agg_values)
                            for g in np.nonzero(cnt)[0]}
                    hk, bk = keyed(hot_warm), keyed(byp_warm)
                    assert set(hk) == set(bk), (hk.keys(), bk.keys())
                    for k in hk:
                        assert hk[k][0] == bk[k][0], f"{k} count"
                        assert np.allclose(hk[k][1:], bk[k][1:],
                                           rtol=1e-5), (k, hk[k], bk[k])
                    # grouped bypass stays keyless: zero key-matrix
                    # rebuilds across warm-up AND the timed rounds
                    # (counter-asserted again below)
                # PAIRED rounds (hot, bypass back-to-back) so driver-box
                # contention cancels in the ratio, as in the main loop
                pairs = []
                for _ in range(rounds):
                    t0 = time.perf_counter()
                    await c.scan(tab, req())
                    hot_t = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    await c.scan_bypass(tab, req())
                    pairs.append((hot_t, time.perf_counter() - t0))
                hot_t = min(h for h, _ in pairs)
                byp_t = min(b for _, b in pairs)
                st = c.last_bypass["stats"] or {}
                key_rebuilds = st.get("key_rebuilds", 0)
                if q.name == "q1_str":
                    # the keyless contract, counter-asserted in the
                    # bench too: grouped bypass must never rebuild a
                    # key matrix (bypass-session-scoped counter)
                    assert key_rebuilds == 0, \
                        f"grouped bypass rebuilt {key_rebuilds} key " \
                        "matrices — the keyless contract broke"
                out["q1" if q.name == "q1_str" else q.name] = {
                    "hotpath_rows_per_s": round(n_li / hot_t, 1),
                    "bypass_rows_per_s": round(n_li / byp_t, 1),
                    # best-of-N over best-of-N, consistent with the
                    # rows/s columns above (a max() of per-pair ratios
                    # would let one stalled hot round mask a real
                    # bypass regression from the WARN tail)
                    "bypass_vs_hotpath": round(hot_t / byp_t, 3),
                    "keyless_blocks": st.get("keyless_blocks"),
                    "blocks": st.get("blocks"),
                    **({"grouped_combine": "combine_grouped_partials",
                        "key_rebuilds": key_rebuilds}
                       if q.name == "q1_str" else {}),
                }
            return out
        finally:
            flags.REGISTRY.reset("bypass_reader_enabled")
            await mc.shutdown()

    try:
        return asyncio.run(run())
    except AssertionError:
        raise   # a parity mismatch IS a bench failure, not a column
    except Exception as e:   # noqa: BLE001 — report, don't fail bench
        return {"error": str(e)[:200]}


def q1_grouped_bench(data, repeats):
    """Dict-key GROUP BY on device vs the interpreted GROUP BY
    (ROADMAP operator-frontier rungs (b)+(d)): TPC-H Q1 over the
    string-keyed lineitem variant (l_returnflag/l_linestatus as real
    STRINGs), streamed end-to-end through the grouped-aggregation
    kernel, against the row-at-a-time interpreter that served every
    string GROUP BY before this PR (``grouped_pushdown_enabled=False``
    is byte-for-byte that path).  Also: the numpy CPU twin
    (ops/grouped_scan.grouped_aggregate_cpu — the parity oracle,
    recorded for the accelerator-box comparison, NOT a WARN ratio on
    this CPU-only image) and a group-cardinality sweep (4 -> 4096
    occupied slots) over synthetic dictionary-coded keys.

    The interpreter chews ~40k rows/s, so the comparison runs on a
    row-capped slice (BENCH_Q1G_ROWS, default 393216 = 6 chunks of
    65536) — both sides measure the SAME table, so the ratio is fair
    and the bench stays bounded."""
    from yugabyte_db_tpu.docdb.operations import ReadRequest
    from yugabyte_db_tpu.models.tpch import (lineitem_str_data,
                                             lineitem_str_info,
                                             numpy_reference,
                                             tpch_q1_str)
    from yugabyte_db_tpu.ops.grouped_scan import (GROUPED_STATS,
                                                  LAST_GROUPED_STATS,
                                                  DictGroupSpec,
                                                  decode_slot_groups,
                                                  grouped_aggregate_cpu,
                                                  make_dict_plan)
    from yugabyte_db_tpu.ops import Expr
    from yugabyte_db_tpu.ops.scan import AggSpec, ScanKernel
    from yugabyte_db_tpu.ops.stream_scan import streaming_scan_aggregate
    from yugabyte_db_tpu.tablet import Tablet
    from yugabyte_db_tpu.utils import flags

    n_g = min(len(data["rowid"]),
              int(os.environ.get("BENCH_Q1G_ROWS", str(6 * 65536))))
    sdata = lineitem_str_data({k: v[:n_g] for k, v in data.items()})
    t = Tablet("lineitem-s", lineitem_str_info(),
               tempfile.mkdtemp(prefix="ybtpu-q1g-"))
    t.bulk_load(sdata, block_rows=65536)
    q = tpch_q1_str()

    def req():
        return ReadRequest("lineitem_s", where=q.where,
                           aggregates=q.aggs, group_by=q.group)

    def by_key(resp):
        counts = np.asarray(resp.group_counts)
        out = {}
        for g in np.nonzero(counts)[0]:
            out[tuple(str(v[g]) for v in resp.group_values)] = \
                (int(counts[g]),) + tuple(
                    float(np.asarray(v)[g]) for v in resp.agg_values)
        return out

    flags.set_flag("streaming_chunk_rows", 65536)
    try:
        launches0 = GROUPED_STATS["launches"]
        grouped_warm = t.read(req())        # compile + warm
        assert grouped_warm.backend == "tpu", "grouped pushdown fell back"
        assert LAST_GROUPED_STATS.get("path") == "streaming", \
            f"expected the STREAMED grouped path, got {LAST_GROUPED_STATS}"
        # paired rounds: grouped and interpreted back-to-back, as in the
        # headline loop, so box contention cancels in the ratio
        rounds = max(2, repeats // 2)
        pairs = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            gresp = t.read(req())
            g_t = time.perf_counter() - t0
            flags.set_flag("grouped_pushdown_enabled", False)
            try:
                t0 = time.perf_counter()
                iresp = t.read(req())
                i_t = time.perf_counter() - t0
            finally:
                flags.REGISTRY.reset("grouped_pushdown_enabled")
            assert iresp.backend == "cpu"
            pairs.append((g_t, i_t))
        gstats = dict(LAST_GROUPED_STATS)
        g_t = min(g for g, _ in pairs)
        i_t = min(i for _, i in pairs)
        # parity: device grouped vs interpreted, keyed by group values
        # (exact counts; l_quantity is integer-valued -> exact int64 SUM
        # lane; fractional price sums carry only f32 representation
        # error, same tolerance ladder as check_q1) — and vs numpy
        ga, ia = by_key(gresp), by_key(iresp)
        assert set(ga) == set(ia), (set(ga), set(ia))
        ref = numpy_reference(q, sdata)
        for k in ga:
            assert ga[k][0] == ia[k][0] == ref[k][2], f"{k} count"
            assert ga[k][1] == ia[k][1] == ref[k][0], f"{k} qty"
            assert abs(ga[k][2] - ref[k][1]) / max(ref[k][1], 1e-9) \
                < 1e-5, f"{k} price"

        # the numpy CPU twin on the same blocks (cold: its own dict plan)
        blocks = []
        for r in t.regular.ssts:
            for i in range(r.num_blocks()):
                blocks.append(r.columnar_block(i))
        cols = sorted(q.columns)

        def twin():
            return grouped_aggregate_cpu(blocks, cols, q.where, q.aggs,
                                         q.group)
        twin_t, (touts, tcounts, tspill) = best_of(twin, rounds)
        assert tspill == 0
        _, tc, tg = decode_slot_groups(
            q.group, make_dict_plan(blocks, q.group.cols).dicts,
            touts, tcounts)
        for i, k in enumerate(zip(*(map(str, g) for g in tg))):
            assert int(tc[i]) == ref[k][2], f"twin {k} count"

        out = {
            "rows": n_g,
            "grouped_rows_per_s": round(n_g / g_t, 1),
            "interp_rows_per_s": round(n_g / i_t, 1),
            "grouped_vs_interp": round(i_t / g_t, 3),
            "twin_rows_per_s": round(n_g / twin_t, 1),
            "vs_cpu_twin": round(twin_t / g_t, 3),
            "kernel_launches": GROUPED_STATS["launches"] - launches0,
            "spill_fallbacks": GROUPED_STATS["spill_fallbacks"],
            "stream_split": gstats,
        }

        # --- group-cardinality sweep: 4 -> 4096 occupied groups -------
        # synthetic dictionary-coded keys, one column per cardinality,
        # ONE table/load; each cardinality lands in its own pow2 slot
        # bucket (8 .. 8192 incl. the spill slot) = one compile each,
        # counted via the fresh kernel's own accounting
        from yugabyte_db_tpu.docdb.table_codec import TableInfo
        from yugabyte_db_tpu.dockv.packed_row import (ColumnSchema,
                                                      ColumnType,
                                                      TableSchema)
        from yugabyte_db_tpu.dockv.partition import PartitionSchema
        cards = [4, 64, 1024, 4096]
        n_sw = 262144
        rng = np.random.default_rng(7)
        sw_schema = TableSchema(
            (ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),)
            + tuple(ColumnSchema(i + 1, f"g{c}", ColumnType.STRING)
                    for i, c in enumerate(cards))
            + (ColumnSchema(len(cards) + 1, "v", ColumnType.FLOAT64),),
            1)
        sw = Tablet("grpsweep", TableInfo(
            "grpsweep", "grpsweep", sw_schema,
            PartitionSchema("hash", 1)),
            tempfile.mkdtemp(prefix="ybtpu-q1gsw-"))
        sw.bulk_load({
            "k": np.arange(n_sw, dtype=np.int64),
            **{f"g{c}": np.array([f"g{i:04d}" for i in range(c)],
                                 object)[rng.integers(0, c, n_sw)]
               for c in cards},
            "v": rng.integers(1, 100, n_sw).astype(np.float64),
        }, block_rows=32768)
        sw_blocks = []
        for r in sw.regular.ssts:
            for i in range(r.num_blocks()):
                sw_blocks.append(r.columnar_block(i))
        skern = ScanKernel()
        sweep = {}
        for i, c in enumerate(cards):
            spec = DictGroupSpec(cols=(i + 1,), max_slots=8192)
            aggs = (AggSpec("sum", Expr.col(len(cards) + 1).node),
                    AggSpec("count"))

            def srun():
                gout = {}
                got = streaming_scan_aggregate(
                    sw_blocks, [i + 1, len(cards) + 1], None, aggs,
                    spec, None, kernel=skern, chunk_rows=32768,
                    grouped_out=gout)
                assert got is not None and gout["spill"] == 0
                return got
            srun()      # compile this slot bucket
            sw_t, _ = best_of(srun, rounds)
            sweep[str(c)] = {
                "rows_per_s": round(n_sw / sw_t, 1),
                "num_slots": LAST_GROUPED_STATS["num_slots"],
                "slots_occupied": LAST_GROUPED_STATS["slots_occupied"],
                "dict_merge_s": LAST_GROUPED_STATS["dict_merge_s"],
                "kernel_s": LAST_GROUPED_STATS["kernel_s"],
            }
        out["cardinality_sweep"] = sweep
        out["sweep_compiles"] = skern.compiles
        return out
    finally:
        flags.REGISTRY.reset("streaming_chunk_rows")


def tpch_join_bench(data, repeats):
    """Device hash join + fused plans (ROADMAP operator-ladder rung
    (c)): a TPC-H Q3/Q5-shaped join+group query — lineitem JOIN orders
    ON l_orderkey = o_orderkey, grouped by the o_orderpriority string
    payload — measured three ways on the SAME table:

      fused        ONE device program per plan signature
                   (filter -> probe -> gather -> group -> aggregate,
                   ops/plan_fusion.py, streamed pow2 chunks)
      per-operator each operator its own program + host round-trip:
                   device filter-pushdown ROW scan materializes the
                   matching probe rows, then a host hash join + numpy
                   group-aggregate (the operator-at-a-time path the
                   fused plan replaces)
      interpreted  join_pushdown_enabled=False — the row-at-a-time
                   CPU join, byte-for-byte the pre-device semantics

    Correctness asserts against direct numpy; the plan-kernel compile
    count is ASSERTED flat across repeated runs AND across a 2x data
    growth at the same plan shape (the pow2-bucket contract).  Row cap
    BENCH_JOIN_ROWS (default 4 chunks of 32768) keeps the interpreted
    leg bounded."""
    from yugabyte_db_tpu.docdb.operations import ReadRequest
    from yugabyte_db_tpu.models.tpch import (PRIO_STRINGS,
                                             generate_orders,
                                             lineitem_join_data,
                                             lineitem_join_info,
                                             numpy_reference_join,
                                             orders_build_wire,
                                             tpch_q3ish)
    from yugabyte_db_tpu.ops.join_scan import (LAST_JOIN_STATS,
                                               hash_join_cpu)
    from yugabyte_db_tpu.ops.plan_fusion import (LAST_PLAN_STATS,
                                                 default_plan_kernel)
    from yugabyte_db_tpu.tablet import Tablet
    from yugabyte_db_tpu.utils import flags

    n_j = min(len(data["rowid"]),
              int(os.environ.get("BENCH_JOIN_ROWS", str(4 * 32768))))
    n_orders = max(n_j // 4, 1)
    odata = generate_orders(n_orders)
    ldata = lineitem_join_data({k: v[:n_j] for k, v in data.items()},
                               n_orders)
    q = tpch_q3ish()
    wire = orders_build_wire(q, odata)
    t = Tablet("lineitem-j", lineitem_join_info(),
               tempfile.mkdtemp(prefix="ybtpu-join-"))
    t.bulk_load(ldata, block_rows=32768)
    flags.set_flag("streaming_chunk_rows", 32768)
    kern = default_plan_kernel()

    def req():
        return ReadRequest("lineitem_j", where=q.probe_where,
                           aggregates=q.aggs, group_by=q.group,
                           join=wire)

    def by_key(resp):
        counts = np.asarray(resp.group_counts)
        return {str(resp.group_values[0][g]):
                (int(counts[g]), float(np.asarray(resp.agg_values[0])[g]))
                for g in np.nonzero(counts)[0]}

    try:
        fused_warm = t.read(req())          # compile + warm
        assert fused_warm.backend == "tpu", "fused join fell back"
        assert LAST_PLAN_STATS.get("path") == "streaming", \
            LAST_PLAN_STATS
        compiles_warm = kern.compiles
        ref = numpy_reference_join(q, ldata, odata)
        fk = by_key(fused_warm)
        for p in PRIO_STRINGS:
            want_c, want_rev = ref[p]
            if want_c == 0:
                assert p not in fk
                continue
            assert fk[p][0] == want_c, (p, fk[p], ref[p])
            assert abs(fk[p][1] - want_rev) / max(want_rev, 1e-9) \
                < 1e-5, (p, fk[p], ref[p])

        # --- per-operator: device row filter, host join+group ---------
        probe_cols = ("l_extendedprice", "l_discount", "l_orderkey")

        def per_operator():
            rows = t.read(ReadRequest(
                "lineitem_j", columns=probe_cols,
                where=q.probe_where)).rows
            ok = np.asarray([r["l_orderkey"] for r in rows], np.int64)
            price = np.asarray([r["l_extendedprice"] for r in rows])
            disc = np.asarray([r["l_discount"] for r in rows])
            midx = hash_join_cpu(ok, np.asarray(wire.keys))
            m = midx >= 0
            prio = np.asarray(wire.payload[list(wire.payload)[0]][0],
                              object)[np.clip(midx, 0, None)]
            rev = price * (1.0 - disc)
            return {p: (int((m & (prio == p)).sum()),
                        float(rev[m & (prio == p)].sum()))
                    for p in PRIO_STRINGS}
        op_warm = per_operator()
        for p in PRIO_STRINGS:
            assert op_warm[p][0] == ref[p][0], (p, op_warm[p], ref[p])

        # paired rounds: fused / per-operator / interpreted
        # back-to-back so box contention cancels in the ratios
        rounds = max(2, repeats // 2)
        trip = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            t.read(req())
            f_t = time.perf_counter() - t0
            t0 = time.perf_counter()
            per_operator()
            o_t = time.perf_counter() - t0
            flags.set_flag("join_pushdown_enabled", False)
            try:
                t0 = time.perf_counter()
                iresp = t.read(req())
                i_t = time.perf_counter() - t0
            finally:
                flags.REGISTRY.reset("join_pushdown_enabled")
            assert iresp.backend == "cpu"
            trip.append((f_t, o_t, i_t))
        f_t = min(x for x, _, _ in trip)
        o_t = min(x for _, x, _ in trip)
        i_t = min(x for _, _, x in trip)
        ik = by_key(iresp)
        assert set(ik) == set(fk)
        for p in fk:
            assert fk[p][0] == ik[p][0], (p, fk[p], ik[p])

        # compile budget: repeated runs at the same plan shape compiled
        # NOTHING new...
        assert kern.compiles == compiles_warm, \
            "plan kernel recompiled at an unchanged plan shape"
        # ...and 2x data growth (same chunk bucket, same build bucket)
        # must not either
        n2 = min(len(data["rowid"]), 2 * n_j)
        ldata2 = lineitem_join_data(
            {k: v[:n2] for k, v in data.items()}, n_orders)
        t2 = Tablet("lineitem-j2", lineitem_join_info(),
                    tempfile.mkdtemp(prefix="ybtpu-join2-"))
        t2.bulk_load(ldata2, block_rows=32768)
        growth = t2.read(req())
        assert growth.backend == "tpu"
        assert kern.compiles == compiles_warm, \
            "plan kernel recompiled on data growth inside the bucket"

        return {
            "rows": n_j,
            "build_rows": int(LAST_PLAN_STATS.get("n_build", 0)),
            "build_slots": int(LAST_PLAN_STATS.get("num_slots", 0)),
            "fused_rows_per_s": round(n_j / f_t, 1),
            "per_operator_rows_per_s": round(n_j / o_t, 1),
            "interp_rows_per_s": round(n_j / i_t, 1),
            "fused_vs_interp": round(i_t / f_t, 3),
            "fused_vs_operator": round(o_t / f_t, 3),
            "plan_compiles": kern.compiles,
            "plan_launches": kern.launches,
            "plan_cache_hits": kern.cache_hits,
            "plan_signatures": len(kern.sig_compiles),
            "compiles_flat_across_growth": True,   # asserted above
            "build_table": dict(LAST_JOIN_STATS),
            "stage_split": {k: v for k, v in LAST_PLAN_STATS.items()
                            if k.endswith("_s") or k == "chunks"},
        }
    finally:
        flags.REGISTRY.reset("streaming_chunk_rows")


def tpch_full_bench(repeats):
    """The whole-query TPC-H gauntlet: EVERY query in the 22-query
    registry (models/tpch.py tpch_queries) through the device path —
    single-table scans and 2-stage fused join chains (lineitem_j ->
    orders_c -> customer, ONE program under one shared visibility
    mask) — with per-query compile budgets ASSERTED and per-query
    fused_vs_interp ratios WARN-wired like any other ratio.

    Inexpressible queries are REPORTED with their typed registry
    reason (table_coverage / subquery_shape / semi_join / outer_join /
    group_domain / expr_shape), never silently skipped.

    Scale: BENCH_TPCH_SF picks the scale factor — default 0.1 (the
    smoke gauntlet); the literal "full" uses the tpch_sf flag (default
    10, the SF10 acceptance gauntlet); 0 skips.  The device leg runs
    the full sf; the interpreted leg replays each query on a
    row-capped clone (BENCH_TPCH_INTERP_ROWS, default 262144) so the
    row-at-a-time baseline stays bounded, with device-vs-interpreted
    PARITY asserted on that same capped clone."""
    from yugabyte_db_tpu.docdb import operations as _ops
    from yugabyte_db_tpu.docdb.operations import ReadRequest
    from yugabyte_db_tpu.models.tpch import (CUSTOMERS_PER_SF,
                                             ORDERS_PER_SF,
                                             ROWS_PER_SF,
                                             _chain_group,
                                             chain_build_wires,
                                             generate_customer,
                                             generate_lineitem,
                                             generate_orders_cust,
                                             lineitem_join_data,
                                             lineitem_join_info,
                                             lineitem_str_data,
                                             lineitem_str_info,
                                             numpy_reference,
                                             numpy_reference_chain,
                                             tpch_queries)
    from yugabyte_db_tpu.ops.plan_fusion import (LAST_PLAN_STATS,
                                                 default_plan_kernel)
    from yugabyte_db_tpu.tablet import Tablet
    from yugabyte_db_tpu.utils import flags

    raw = os.environ.get("BENCH_TPCH_SF", "0.1")
    sf = float(flags.get("tpch_sf")) if raw == "full" else float(raw)
    if sf <= 0:
        return None
    n = int(ROWS_PER_SF * sf)
    n_orders = max(int(ORDERS_PER_SF * sf), 1)
    n_cust = max(int(CUSTOMERS_PER_SF * sf), 1)
    n_cap = min(n, int(os.environ.get("BENCH_TPCH_INTERP_ROWS",
                                      str(262144))))
    data = generate_lineitem(sf)
    ldata = lineitem_join_data(data, n_orders)
    odata = generate_orders_cust(n_orders, n_cust)
    cdata = generate_customer(n_cust)
    base = tempfile.mkdtemp(prefix="ybtpu-tpch-full-")
    block_rows = 65536
    t_j = Tablet("li-full-j", lineitem_join_info(), f"{base}/j")
    t_j.bulk_load(ldata, block_rows=block_rows)
    t_s = Tablet("li-full-s", lineitem_str_info(), f"{base}/s")
    t_s.bulk_load(lineitem_str_data(data), block_rows=block_rows)
    cap_l = {k: v[:n_cap] for k, v in ldata.items()}
    cap_d = {k: v[:n_cap] for k, v in data.items()}
    t_jc = Tablet("li-cap-j", lineitem_join_info(), f"{base}/jc")
    t_jc.bulk_load(cap_l, block_rows=32768)
    t_sc = Tablet("li-cap-s", lineitem_str_info(), f"{base}/sc")
    t_sc.bulk_load(lineitem_str_data(cap_d), block_rows=32768)
    flags.set_flag("streaming_chunk_rows", min(block_rows, 1 << 20))
    # chain build sides at TPC-H scale are FACT-sized (orders is
    # 1.5M/SF; q3 ships ~45% of them) — raise the build cap to the
    # pow2 hard maximum so the gauntlet measures the device path
    # instead of refusing it.  Bucket growth across SFs is exactly
    # what the plan signature absorbs (one compile per bucket).
    flags.set_flag("join_max_build_slots", 1 << 24)
    pkern = default_plan_kernel()
    skern = _ops._SHARED_KERNEL
    rounds = max(2, repeats // 2)

    def by_key(resp):
        counts = np.asarray(resp.group_counts)
        return {tuple(str(gv[g]) for gv in resp.group_values):
                (int(counts[g]),
                 float(np.asarray(resp.agg_values[0])[g]))
                for g in np.nonzero(counts)[0]}

    def run_query(e):
        q = e.spec
        if e.kind == "chain":
            wires = chain_build_wires(q, odata, cdata)
            tab, tab_cap = t_j, t_jc
            interp_flag = "join_pushdown_enabled"

            def req():
                return ReadRequest("lineitem_j", where=q.probe_where,
                                   aggregates=q.aggs,
                                   group_by=_chain_group(q.group_col),
                                   join=wires)
            ref = numpy_reference_chain(q, cap_l, odata, cdata)
        else:
            tab, tab_cap = ((t_s, t_sc) if q.name == "q1_str"
                            else (t_j, t_jc))
            interp_flag = "tpu_pushdown_enabled"

            def req():
                return ReadRequest(tab.info.name,
                                   where=q.where, aggregates=q.aggs,
                                   group_by=q.group)
            ref = numpy_reference(q, cap_d)

        # warm (compile) then timed rounds with the compile count
        # ASSERTED flat — the per-query compile budget
        warm = tab.read(req())
        assert warm.backend == "tpu", \
            f"{e.name}: device path fell back ({warm.backend})"
        c_p, c_s = pkern.compiles, skern.compiles
        best = None
        for _ in range(rounds):
            t0 = time.perf_counter()
            tab.read(req())
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        assert pkern.compiles == c_p and skern.compiles == c_s, \
            f"{e.name}: recompiled at an unchanged plan shape"
        split = {k: v for k, v in LAST_PLAN_STATS.items()
                 if k.endswith("_s") or k in ("chunks", "join_stages",
                                              "num_slots")} \
            if e.kind == "chain" else {}

        # parity + fused_vs_interp on the capped clone (paired rounds)
        pairs = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            dresp = tab_cap.read(req())
            d_t = time.perf_counter() - t0
            flags.set_flag(interp_flag, False)
            try:
                t0 = time.perf_counter()
                iresp = tab_cap.read(req())
                i_t = time.perf_counter() - t0
            finally:
                flags.REGISTRY.reset(interp_flag)
            pairs.append((d_t, i_t))
        assert dresp.backend == "tpu" and iresp.backend == "cpu", \
            (e.name, dresp.backend, iresp.backend)
        if e.kind == "chain" or q.group is not None:
            dk, ik = by_key(dresp), by_key(iresp)
            assert set(dk) == set(ik), (e.name, set(dk) ^ set(ik))
            for g in dk:
                assert dk[g][0] == ik[g][0], (e.name, g, dk[g], ik[g])
            want = ({(str(g),): c for g, (c, _) in ref.items() if c}
                    if e.kind == "chain" else None)
            if want is not None:
                assert {g: c for g, (c, _) in dk.items()} == want, \
                    (e.name, dk, want)
        else:
            dv = float(np.asarray(dresp.agg_values[0]))
            iv = float(np.asarray(iresp.agg_values[0]))
            assert abs(dv - iv) / max(abs(iv), 1e-9) < 1e-5, \
                (e.name, dv, iv)
            assert abs(dv - ref) / max(abs(ref), 1e-9) < 1e-5, \
                (e.name, dv, ref)
        return {
            "kind": e.kind, "note": e.note, "rows": n,
            "rows_per_s": round(n / best, 1),
            "interp_rows_per_s": round(n_cap / min(i for _, i in pairs),
                                       1),
            "fused_vs_interp": round(
                max(i / d for d, i in pairs), 3),
            "new_compiles_after_warm": 0,   # asserted above
            **({"stage_split": split} if split else {}),
        }

    out = {"sf": sf, "rows": n, "orders": n_orders,
           "customers": n_cust, "interp_cap_rows": n_cap,
           "queries": {}}
    try:
        for name, e in tpch_queries().items():
            if e.kind == "inexpressible":
                out["queries"][name] = {"inexpressible": e.reason,
                                        "note": e.note}
                continue
            out["queries"][name] = run_query(e)
        out["expressible"] = sorted(
            k for k, v in out["queries"].items()
            if "inexpressible" not in v)
        out["plan_compiles_per_signature"] = \
            sorted(pkern.sig_compiles.values())
    finally:
        flags.REGISTRY.reset("streaming_chunk_rows")
        flags.REGISTRY.reset("join_max_build_slots")
    return out


def trace_overhead_bench():
    """The observability layer must not tax the hot path it observes
    (ISSUE 14 acceptance: headline rates within 2% with tracing at
    default sampling).  Paired interleaved rounds through the REAL RPC
    path (MiniCluster): YCSB-shaped point read/write ops and a
    Q6-shaped aggregate scan, measured with trace_sampling_rate=0 vs
    the flag DEFAULT (plus the ASH sampler thread running, as in a
    real server).  `trace_ycsb_on_vs_off` / `trace_q6_on_vs_off` are
    best-of-round ratios WARN-wired below 0.98.  BENCH_TRACE_S=0
    skips."""
    import asyncio

    dur = float(os.environ.get("BENCH_TRACE_S", "1.0"))
    if dur <= 0:
        return None

    async def run():
        from yugabyte_db_tpu.docdb.operations import ReadRequest, RowOp
        from yugabyte_db_tpu.docdb.table_codec import TableInfo
        from yugabyte_db_tpu.dockv.packed_row import (
            ColumnSchema, ColumnType, TableSchema)
        from yugabyte_db_tpu.dockv.partition import PartitionSchema
        from yugabyte_db_tpu.ops.scan import AggSpec
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
        from yugabyte_db_tpu.utils import flags as _flags
        from yugabyte_db_tpu.utils.trace import ASH

        info = TableInfo("", "tracebench", TableSchema(columns=(
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "v", ColumnType.FLOAT64)), version=1),
            PartitionSchema("hash", 1))
        mc = await MiniCluster(tempfile.mkdtemp(prefix="ybtpu-trace-"),
                               num_tservers=1).start()
        default_rate = None
        try:
            c = mc.client()
            await c.create_table(info, num_tablets=1,
                                 replication_factor=1)
            await mc.wait_for_leaders("tracebench")
            n_rows = 50_000
            for lo in range(0, n_rows, 5000):
                await c.insert("tracebench", [
                    {"k": i, "v": float(i)}
                    for i in range(lo, lo + 5000)])
            agg_req = ReadRequest(
                (await c._table("tracebench")).info.table_id,
                aggregates=(AggSpec("count"), AggSpec("sum", ("col", 1))))
            # the sampler thread runs during BOTH sides (a real server
            # always has it); only root sampling is toggled
            ASH.start()

            async def ycsb_round():
                ops = 0
                stop = time.monotonic() + dur

                async def worker(base):
                    nonlocal ops
                    i = base
                    while time.monotonic() < stop:
                        if i % 4 == 0:
                            await c.write("tracebench", [RowOp(
                                "upsert", {"k": i % n_rows,
                                           "v": float(i)})])
                        else:
                            await c.get("tracebench",
                                        {"k": i % n_rows})
                        ops += 1
                        i += 7
                t0 = time.perf_counter()
                await asyncio.gather(*[worker(j * 131) for j in range(8)])
                return ops / (time.perf_counter() - t0)

            async def q6_round():
                scans = 0
                t0 = time.perf_counter()
                while time.perf_counter() - t0 < dur:
                    await c.scan("tracebench", agg_req)
                    scans += 1
                return scans * n_rows / (time.perf_counter() - t0)

            default_rate = _flags.REGISTRY._flags[
                "trace_sampling_rate"].default
            sides = {"off": 0.0, "on": default_rate}
            res = {"off": {"ycsb": [], "q6": []},
                   "on": {"ycsb": [], "q6": []}}
            # warm both paths (kernel compile + connection setup)
            await ycsb_round()
            await q6_round()
            for _ in range(2):          # paired, interleaved
                for side, rate in sides.items():
                    _flags.set_flag("trace_sampling_rate", rate)
                    res[side]["ycsb"].append(await ycsb_round())
                    res[side]["q6"].append(await q6_round())
            return {
                "seconds_per_round": dur,
                "default_sampling_rate": default_rate,
                "ycsb_ops_per_s_off": round(max(res["off"]["ycsb"]), 1),
                "ycsb_ops_per_s_on": round(max(res["on"]["ycsb"]), 1),
                "q6_rows_per_s_off": round(max(res["off"]["q6"]), 1),
                "q6_rows_per_s_on": round(max(res["on"]["q6"]), 1),
                "trace_ycsb_on_vs_off": round(
                    max(res["on"]["ycsb"]) / max(res["off"]["ycsb"]), 3),
                "trace_q6_on_vs_off": round(
                    max(res["on"]["q6"]) / max(res["off"]["q6"]), 3),
            }
        finally:
            from yugabyte_db_tpu.utils import flags as _flags2
            if default_rate is not None:
                _flags2.set_flag("trace_sampling_rate", default_rate)
            await mc.shutdown()

    try:
        return asyncio.run(run())
    except Exception as e:   # noqa: BLE001 — report, don't fail bench
        if os.environ.get("BENCH_DEBUG"):
            raise
        return {"error": str(e)[:300]}


# ratio keys whose value < 1.0 means "slower than the baseline it was
# measured against" — surfaced as a WARN in the bench tail instead of
# sitting silently inside the JSON (satellite of PR 3; Q6's r05
# vs_baseline of 0.923 went unnoticed for a round)
_RATIO_KEYS = ("vs_baseline", "speedup", "vs_cpu", "vs_xla",
               "shred_vs_interp",
               "p99_ratio_on_vs_off", "achieved_ratio_on_vs_off",
               "stream_vs_mono", "v2_vs_v1_bytes", "prune_speedup",
               "bypass_vs_hotpath", "bypass_p99_impact",
               "grouped_vs_interp", "fused_vs_interp",
               "fused_vs_operator", "split_goodput_ratio",
               "cluster_bypass_p95_impact", "cluster_p99_on_vs_off",
               "cluster_achieved_on_vs_off", "cluster_p99_spread",
               "cluster_fused_p99_on_vs_off",
               "cluster_fused_achieved_on_vs_off",
               "trace_ycsb_on_vs_off", "trace_q6_on_vs_off",
               "matview_vs_rescan")

#: keys where ANY nonzero value is a regression (acked data vanished
#: or corrupted across a chaos round — never acceptable)
_NONZERO_BAD_KEYS = ("chaos_missing", "chaos_mismatched",
                     "chaos_unreachable")


def warn_regressed_ratios(node, path="", out=None):
    """Collect (path, value) for every ratio key below 1.0."""
    if out is None:
        out = []
    if isinstance(node, dict):
        for k, v in node.items():
            p = f"{path}.{k}" if path else k
            if k in _RATIO_KEYS and isinstance(v, (int, float)):
                # p99_ratio: LOWER is better (scheduler holds latency);
                # bypass_p99_impact: the bypass thread must not inflate
                # the hot path's write p99 past CPU-contention noise
                # (2x on this 2-core box — queueing coupling would show
                # as 10x+); everything else: below 1.0 is a regression
                if k == "p99_ratio_on_vs_off":
                    bad = v > 0.5
                elif k == "bypass_p99_impact":
                    bad = v > 2.0
                elif k == "cluster_bypass_p95_impact":
                    # the gate rides the p95 ratio, not p99: on 2
                    # cores a round's p99 is its ~50th-highest sample
                    # and flush-pause spikes swing it ~20x run to run
                    # (p99_ms_rounds records the spread), while the
                    # p95 medians hold steady; a REAL event-loop
                    # coupling reads 10x+ either way
                    bad = v > 2.0
                elif k == "cluster_p99_on_vs_off":
                    # cross-process: driver p99 includes client
                    # backoff/retry; the bar is "scheduler ON is not
                    # WORSE", with headroom for 2-core noise
                    bad = v > 1.5
                elif k == "cluster_achieved_on_vs_off":
                    # tightened from 0.9 in PR 11: the fusion levers
                    # (async flush, fused appends, cross-tablet
                    # dispatch) are claimed — scheduler ON must now
                    # WIN at matched goodput, not merely tie
                    bad = v < 1.0
                elif k == "cluster_fused_achieved_on_vs_off":
                    bad = v < 1.0
                elif k == "cluster_fused_p99_on_vs_off":
                    # fusion ON must not worsen the write p99 (2-core
                    # noise headroom mirrors cluster_p99_on_vs_off)
                    bad = v > 1.5
                elif k == "cluster_p99_spread":
                    # per-round p99 max/median: flush-pause luck made
                    # this ~20x pre-async-flush; the PR-11 bar is 3x
                    bad = v > 3.0
                elif k == "split_goodput_ratio":
                    # goodput through a live split+rebalance may dip,
                    # but collapsing past 4x is a control-plane stall
                    bad = v < 0.25
                elif k in ("trace_ycsb_on_vs_off",
                           "trace_q6_on_vs_off"):
                    # tracing at DEFAULT sampling may cost at most 2%
                    # of the hot path it observes (ISSUE 14 overhead
                    # gate; 0.98 = the 2% bar)
                    bad = v < 0.98
                else:
                    bad = v < 1.0
                if bad:
                    out.append((p, v))
            elif k in _NONZERO_BAD_KEYS and isinstance(v, (int, float)):
                if v > 0:
                    out.append((p, v))
            else:
                warn_regressed_ratios(v, p, out)
    elif isinstance(node, list):
        for i, v in enumerate(node):
            warn_regressed_ratios(v, f"{path}[{i}]", out)
    return out


def warn_suppression_growth(base_dir=None):
    """Collect WARN lines when the static-analysis suppression count
    grew past tools/analyze/baseline.json — annotations accreting
    instead of hazards being fixed is its own regression — or when the
    sweep's own wall clock grew past 1.5x the recorded
    ``analyze_wall_ms`` (the engine rides in tier-1 and the pre-commit
    hook; its cost is tracked like any hot path)."""
    here = base_dir or os.path.dirname(os.path.abspath(__file__))
    out = []
    try:
        sys.path.insert(0, os.path.join(here, "tools"))
        try:
            from analyze import ALL_PASSES, ProjectIndex, run_analysis
        finally:
            sys.path.pop(0)
        with open(os.path.join(here, "tools", "analyze",
                               "baseline.json")) as f:
            base = json.load(f)
        baseline = base["suppressions"]
        report = run_analysis(ProjectIndex(
            here, cache_dir=os.path.join(here, ".analyze_cache")),
            ALL_PASSES)
        for pass_id, n in sorted(report["suppressions"].items()):
            if n > baseline.get(pass_id, 0):
                out.append(
                    f"analysis suppressions for {pass_id} grew to {n} "
                    f"(baseline {baseline.get(pass_id, 0)}) — fix the "
                    f"hazard or commit a new baseline deliberately")
        base_ms = base.get("analyze_wall_ms")
        if base_ms and report["wall_ms"] > 1.5 * base_ms:
            out.append(
                f"analyze_wall_ms grew to {report['wall_ms']:.0f} "
                f"(baseline {base_ms}, limit 1.5x) — the analysis "
                f"engine's own cost regressed; profile the passes or "
                f"re-record the baseline deliberately")
    except Exception as e:   # noqa: BLE001 — account, don't fail bench
        out.append(f"analysis suppression check failed: {e!r:.120}")
    return out


def _logical_row_bytes(info) -> int:
    """User-data bytes per row straight from the schema (fixed-width
    columns only — the lineitem shape): the write-amp denominator,
    so 'bytes written / logical bytes' is comparable across formats."""
    from yugabyte_db_tpu.dockv.packed_row import ColumnType
    return sum(ColumnType.FIXED_WIDTHS.get(c.type, 8)
               for c in info.schema.columns)


def _make_compaction_tablet(data, n_ssts, rows_per_sst, tag):
    """A tablet with `n_ssts` SSTables: sequential loads with 25%
    overlapping (re-written) keys so the merge has real MVCC work
    (BASELINE config 4; reference: 100-SST major compaction,
    rocksdb/db/compaction_job.cc:665)."""
    from yugabyte_db_tpu.models.tpch import LineitemTable
    from yugabyte_db_tpu.utils.hybrid_time import HybridTime
    t = LineitemTable(tempfile.mkdtemp(prefix=f"ybtpu-comp-{tag}-"),
                      num_tablets=1).tablets[0]
    n = len(data["rowid"])
    base_us = int(time.time() * 1e6)
    for i in range(n_ssts):
        # 75% fresh rows, 25% re-writes of the previous batch's keys
        fresh = (i * rows_per_sst) % max(n - rows_per_sst, 1)
        sel = np.arange(fresh, fresh + rows_per_sst) % n
        if i > 0:
            prev = (sel - rows_per_sst // 4) % n
            sel[: rows_per_sst // 4] = prev[: rows_per_sst // 4]
        batch = {k: v[sel] for k, v in data.items()}
        t.bulk_load(batch, ht=HybridTime.from_micros(base_us + i * 1000))
    assert len(t.regular.ssts) >= n_ssts
    return t


def doc_scan_bench(repeats):
    """Document shredding (docstore/): a selective path predicate +
    aggregates over ~1M JSON documents, shredded v2 lanes on the
    device path vs the interpreted row-at-a-time JSON extractor
    (``doc_shred_enabled=False`` at read time is byte-for-byte that
    path over the SAME SSTs).  The request exercises the int-path
    compare, the exact int64 SUM over a shredded lane, and the
    dict-code MAX decode satellite in one shot; shred_coverage (the
    fraction of scanned rows served from shredded lanes) is asserted
    nonzero and shred_vs_interp WARN-wires like stream_vs_mono.
    Interpreted rounds cost ~10s/M rows, so the interpreted side runs
    once (the >=10x margin dwarfs round noise)."""
    from yugabyte_db_tpu.docdb.operations import ReadRequest
    from yugabyte_db_tpu.docstore import (DOC_STATS, DOC_WRITE_STATS,
                                          LAST_DOC_STATS)
    from yugabyte_db_tpu.models.docbench import (doc_qty_query,
                                                 docs_info,
                                                 generate_docs)
    from yugabyte_db_tpu.tablet import Tablet
    from yugabyte_db_tpu.utils import flags

    n = int(os.environ.get("BENCH_DOC_ROWS", str(1_000_000)))
    data = generate_docs(n)
    t = Tablet("docs-bench", docs_info(),
               tempfile.mkdtemp(prefix="ybtpu-doc-"))
    t0 = time.perf_counter()
    t.bulk_load(data, block_rows=65536)
    load_s = time.perf_counter() - t0
    where, aggs = doc_qty_query()

    def req():
        return ReadRequest("docs", where=where, aggregates=aggs)

    warm = t.read(req())                   # compile + warm
    assert warm.backend == "tpu", \
        f"doc pushdown fell back: {DOC_STATS}"
    coverage = LAST_DOC_STATS.get("coverage", 0.0)
    assert coverage > 0, f"shred_coverage {coverage}"
    shred_ts = []
    for _ in range(max(2, repeats)):
        t0 = time.perf_counter()
        sresp = t.read(req())
        shred_ts.append(time.perf_counter() - t0)
    flags.set_flag("doc_shred_enabled", False)
    try:
        t0 = time.perf_counter()
        iresp = t.read(req())
        interp_t = time.perf_counter() - t0
    finally:
        flags.REGISTRY.reset("doc_shred_enabled")
    assert iresp.backend == "cpu"
    a = [np.asarray(v).tolist() for v in sresp.agg_values]
    b = [np.asarray(v).tolist() for v in iresp.agg_values]
    assert a == b, f"doc shredded/interpreted parity: {a} != {b}"
    shred_t = min(shred_ts)
    return {
        "rows": n, "load_s": round(load_s, 2),
        "agg_values": a,
        "shred_rows_per_s": round(n / shred_t, 1),
        "interp_rows_per_s": round(n / interp_t, 1),
        "shred_s": round(shred_t, 4),
        "interp_s": round(interp_t, 4),
        "shred_vs_interp": round(interp_t / shred_t, 2),
        "shred_coverage": coverage,
        "paths_referenced": LAST_DOC_STATS.get("paths"),
        "write_stats": dict(DOC_WRITE_STATS),
        "fallback_reasons": dict(DOC_STATS.get("reasons", {})),
    }


def main():
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    repeats = int(os.environ.get("BENCH_REPEATS", "5"))

    device_fallback = False
    probe_log = []
    if not os.environ.get("YBTPU_PLATFORM"):
        ok, probe_log = probe_device()
        if not ok:
            # accelerator unreachable: still produce a benchmark line on
            # CPU — with a virtual 8-device host platform so the
            # distributed psum path is exercised for real
            os.environ["YBTPU_PLATFORM"] = "cpu"
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") +
                " --xla_force_host_platform_device_count=8")
            device_fallback = True

    import jax
    from yugabyte_db_tpu.models.tpch import (
        LineitemTable, TPCH_Q1, TPCH_Q6, generate_lineitem, numpy_reference,
    )
    from yugabyte_db_tpu.ops.cpu_scan import cpu_scan_aggregate
    from yugabyte_db_tpu.ops.device_batch import build_batch
    from yugabyte_db_tpu.ops.scan import ScanKernel
    from yugabyte_db_tpu.utils import flags

    dev = jax.devices()[0]
    data = generate_lineitem(sf)
    n = len(data["rowid"])

    tmp = tempfile.mkdtemp(prefix="ybtpu-bench-")
    table = LineitemTable(tmp, num_tablets=1)
    t0 = time.perf_counter()
    loaded = table.load(data)
    load_s = time.perf_counter() - t0
    tablet = table.tablets[0]

    # --- bulk-load output-byte accounting (v2 format satellite) ---------
    # logical bytes = raw user column data; write-amp is what the
    # on-disk format adds on top (keys/MVCC/index/bloom). The small v1
    # comparison load yields v2_vs_v1_bytes (>= 1.0 means v2 is
    # smaller), surfacing byte regressions like speed regressions.
    lrb = _logical_row_bytes(table.info)
    out_bytes = sum(r.file_size for r in tablet.regular.ssts)
    flags.set_flag("sst_format_version", 1)
    try:
        v1_table = LineitemTable(tempfile.mkdtemp(prefix="ybtpu-v1-"),
                                 num_tablets=1)
        v1_table.load(data)
        v1_bytes = sum(r.file_size
                       for r in v1_table.tablets[0].regular.ssts)
    finally:
        flags.REGISTRY.reset("sst_format_version")
    bulk_load_block = {
        "rows": loaded, "load_rows_per_s": round(loaded / load_s, 1),
        "output_bytes": out_bytes,
        "output_bytes_per_row": round(out_bytes / max(loaded, 1), 2),
        "write_amp": round(out_bytes / max(loaded * lrb, 1), 3),
        "v1_output_bytes_per_row": round(v1_bytes / max(loaded, 1), 2),
        "v2_vs_v1_bytes": round(v1_bytes / max(out_bytes, 1), 3),
        "format_version": flags.get("sst_format_version"),
    }

    blocks = []
    for r in tablet.regular.ssts:
        for i in range(r.num_blocks()):
            blocks.append(r.columnar_block(i))

    def check_q1(sums, counts, ref):
        """sums: list of per-group arrays (5 aggs), counts: [6].

        Tolerances derive from the engine's documented accumulation
        contract (ops/scan.py): SUM accumulates EXACTLY in int64 fixed
        point on every backend, so integer-valued columns (l_quantity)
        are exact and counts are exact. Fractional sums carry only the
        per-row f32 device representation error (<= 2^-24 relative per
        row — all-positive terms, so <= ~1.2e-7 on the sum) plus
        <= 1e-12 quantization; 1e-5 keeps two orders of margin without
        re-admitting accumulation drift."""
        for g in range(6):
            want_qty, want_price, want_cnt = ref[g]
            assert int(counts[g]) == want_cnt, f"q1 g{g} count"
            assert abs(float(sums[0][g]) - want_qty) \
                <= 1e-9 * max(abs(want_qty), 1), \
                f"q1 g{g} qty: {float(sums[0][g])} vs {want_qty}"
            rel = abs(float(sums[1][g]) - want_price) / max(want_price, 1e-9)
            assert rel < 1e-5, f"q1 g{g} price: {float(sums[1][g])} vs " \
                f"{want_price}"

    results = {}
    results["bulk_load"] = bulk_load_block
    kernel = ScanKernel()
    for q in (TPCH_Q6, TPCH_Q1):
        batch = build_batch(blocks, sorted(q.columns))

        def cpu_run():
            return cpu_scan_aggregate(blocks, q.columns, q.where,
                                      q.aggs, q.group)

        def tpu_run():
            outs, counts, _ = kernel.run(batch, q.where, q.aggs, q.group)
            jax.block_until_ready(outs)
            return outs, counts
        tpu_run()   # compile + warm
        cpu_run()   # page-cache warm for the baseline too
        # PAIRED measurement (VERDICT r5 item 2): kernel and baseline
        # run BACK-TO-BACK inside each round, so driver-box contention
        # hits both sides of a round equally and cancels in the ratio.
        # vs_baseline is the best-of-N of the per-round RATIO (raw
        # best-of-N times ride along for absolute rates); three rounds
        # of vs_baseline < 1.0 were contention noise, not the engine.
        pairs = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            tpu_out, tpu_counts = tpu_run()
            tpu_r = time.perf_counter() - t0
            t0 = time.perf_counter()
            cpu_run()
            cpu_r = time.perf_counter() - t0
            pairs.append((tpu_r, cpu_r))
        tpu_t = min(t for t, _ in pairs)
        cpu_t = min(c for _, c in pairs)
        ratios = [c / t for t, c in pairs]
        # correctness vs direct numpy — BOTH queries
        ref = numpy_reference(q, data)
        if q.name == "q6":
            # sum of f32 products of two f32 values: per-row rel error
            # <= 3*2^-24 ~ 1.8e-7, all-positive terms, exact int64
            # accumulation => 1e-5 has ~50x margin
            rel = abs(float(tpu_out[0]) - ref) / max(abs(ref), 1e-9)
            assert rel < 1e-5, f"q6 mismatch: {float(tpu_out[0])} vs {ref}"
        else:
            check_q1([np.asarray(o) for o in tpu_out],
                     np.asarray(tpu_counts), ref)
        results[q.name] = {
            "cpu_s": cpu_t, "tpu_s": tpu_t,
            "cpu_rows_per_s": n / cpu_t, "tpu_rows_per_s": n / tpu_t,
            "speedup": max(ratios),
            "ratio_rounds": [round(r, 3) for r in ratios],
        }

    # --- the bypass column: Q1/Q6 through client.scan_bypass ------------
    bp = tpch_bypass_bench(data, repeats)
    for qn in ("q6", "q1"):
        if bp is None:
            results[qn]["bypass"] = "skipped (BENCH_TPCH_BYPASS=0)"
        elif "error" in bp:
            results[qn]["bypass"] = {"error": bp["error"]}
        else:
            results[qn]["bypass"] = bp[qn]

    # --- cold-scan split: streaming chunk pipeline vs monolithic batch --
    # The headline q6/q1 numbers above are WARM-scan rates (batch already
    # on device; kernel time only).  A COLD scan also pays batch
    # formation — decode + concat + pad + device_put — which the r05
    # monolithic path ran serially before the first kernel byte.  This
    # block measures both cold paths (monolithic = r05 behavior =
    # streaming_scan_enabled=False; streaming = pow2-chunk pipeline with
    # batch formation overlapped against kernel dispatch) and reports
    # the batch-build vs kernel time split, so batch-formation wins are
    # visible separately from kernel wins.
    from yugabyte_db_tpu.ops.stream_scan import (LAST_STREAM_STATS,
                                                 streaming_scan_aggregate)
    cold_results = {}
    for q in (TPCH_Q6, TPCH_Q1):
        cols = sorted(q.columns)
        mono_build_s = [0.0]

        def mono_cold():
            t0 = time.perf_counter()
            b = build_batch(blocks, cols)
            mono_build_s[0] = time.perf_counter() - t0
            outs, counts, _ = kernel.run(b, q.where, q.aggs, q.group)
            jax.block_until_ready(outs)
            return outs

        def stream_cold():
            return streaming_scan_aggregate(blocks, cols, q.where,
                                            q.aggs, q.group,
                                            kernel=kernel)
        if stream_cold() is None:   # compile; None = too few chunks to
            # stream (tiny BENCH_SF) — the cold comparison is mono-only
            cold_results[q.name] = {"stream": "declined (too few chunks)"}
            continue
        rounds = max(2, repeats // 2)
        mono_rounds = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            mono_cold()
            mono_rounds.append((time.perf_counter() - t0,
                                mono_build_s[0]))
        mono_t, mono_build = min(mono_rounds)   # split from the SAME round
        stream_t, (souts, scounts) = best_of(stream_cold, rounds)
        if q.name == "q6":
            ref = numpy_reference(q, data)
            rel = abs(float(souts[0]) - ref) / max(abs(ref), 1e-9)
            assert rel < 1e-5, f"q6 stream mismatch: {float(souts[0])}"
        else:
            check_q1([np.asarray(o) for o in souts],
                     np.asarray(scounts), numpy_reference(q, data))
        cold_results[q.name] = {
            "mono_rows_per_s": round(n / mono_t, 1),
            "stream_rows_per_s": round(n / stream_t, 1),
            "stream_vs_mono": round(mono_t / stream_t, 3),
            "mono_split": {"batch_build_s": round(mono_build, 4),
                           "kernel_s": round(mono_t - mono_build, 4)},
            "stream_split": dict(LAST_STREAM_STATS),
        }
    # --- zone-map pruning on a selective Q6-style scan ------------------
    # Hash sharding scrambles rowid across blocks, so the prune scenario
    # uses the range-sharded clone (rowid-clustered blocks): Q6's
    # predicates plus a selective rowid range. Paired ON/OFF rounds;
    # the skipped-block counter comes from the streaming stats.
    try:
        from yugabyte_db_tpu.docdb.operations import (
            LAST_SCAN_PRUNE_STATS, ReadRequest)
        from yugabyte_db_tpu.models.tpch import lineitem_range_info
        from yugabyte_db_tpu.ops import Expr
        from yugabyte_db_tpu.ops.stream_scan import LAST_STREAM_STATS
        from yugabyte_db_tpu.tablet import Tablet
        from yugabyte_db_tpu.utils.hybrid_time import HybridTime
        from yugabyte_db_tpu.models.tpch import ROWID, TPCH_Q6

        rt = Tablet("lineitem-range", lineitem_range_info(),
                    tempfile.mkdtemp(prefix="ybtpu-zp-"))
        rt.bulk_load(data, ht=HybridTime.from_micros(
            int(time.time() * 1e6)))
        hi = n // 8
        zwhere = ("and", TPCH_Q6.where,
                  (Expr.col(ROWID) < hi).node)
        zreq = ReadRequest("lineitem_r", where=zwhere,
                           aggregates=TPCH_Q6.aggs)

        def zp_round():
            return rt.read(zreq)

        zp_round()   # compile + warm
        on_t, on_r = best_of(zp_round, max(2, repeats // 2))
        skipped = (LAST_STREAM_STATS.get("zone_blocks_pruned")
                   or LAST_SCAN_PRUNE_STATS.get("blocks_pruned", 0))
        total_blk = (LAST_STREAM_STATS.get("zone_blocks_total")
                     or LAST_SCAN_PRUNE_STATS.get("blocks_total", 0))
        flags.set_flag("zone_map_pruning", False)
        try:
            zp_round()   # warm the unpruned batches too
            off_t, off_r = best_of(zp_round, max(2, repeats // 2))
        finally:
            flags.REGISTRY.reset("zone_map_pruning")
        m = ((data["l_shipdate"] >= 8766) & (data["l_shipdate"] < 9131)
             & (data["l_discount"] >= 0.05) & (data["l_discount"] <= 0.07)
             & (data["l_quantity"] < 24.0) & (data["rowid"] < hi))
        ref = (data["l_extendedprice"][m] * data["l_discount"][m]).sum()
        for r in (on_r, off_r):
            rel = abs(float(np.asarray(r.agg_values[0])) - ref) \
                / max(abs(ref), 1e-9)
            assert rel < 1e-5, f"zone-prune q6 mismatch: {rel}"
        cold_results["zone_prune_q6"] = {
            "selectivity": round(hi / n, 3),
            "blocks_skipped": int(skipped),
            "blocks_total": int(total_blk),
            "on_s": round(on_t, 4), "off_s": round(off_t, 4),
            "prune_speedup": round(off_t / on_t, 3),
        }
    except Exception as e:   # noqa: BLE001 — report, don't fail bench
        cold_results["zone_prune_q6"] = {"error": str(e)[:200]}
    results["cold_scan"] = cold_results

    # --- q1_grouped: dict-key GROUP BY kernel vs interpreted ------------
    # (operator-frontier rungs (b)+(d): string group keys aggregate on
    # device over scan-global dictionary codes; grouped_vs_interp
    # WARN-wires like stream_vs_mono)
    results["q1_grouped"] = q1_grouped_bench(data, repeats)

    # --- document shredding: path predicates over JSON as columnar
    # lanes vs the interpreted extractor (docstore/) -------------------
    try:
        results["doc_scan"] = doc_scan_bench(repeats)
    except AssertionError:
        raise   # a parity/coverage break IS a bench failure
    except Exception as e:   # noqa: BLE001 — report, don't fail bench
        if os.environ.get("BENCH_DEBUG"):
            raise
        results["doc_scan"] = {"error": str(e)[:300]}

    # --- device hash join + fused plans (Q3/Q5-shaped join+group) -------
    try:
        results["tpch_join"] = tpch_join_bench(data, repeats)
    except AssertionError:
        raise   # a parity/compile-budget break IS a bench failure
    except Exception as e:   # noqa: BLE001 — report, don't fail bench
        if os.environ.get("BENCH_DEBUG"):
            raise
        results["tpch_join"] = {"error": str(e)[:300]}

    # --- whole-query gauntlet: the 22-query TPC-H registry --------------
    # (BENCH_TPCH_SF sets the scale — 0.1 smoke default, "full" = the
    # tpch_sf flag's SF10, 0 skips; inexpressible queries report typed
    # reasons, fused_vs_interp WARN-wires per query)
    try:
        tf = tpch_full_bench(repeats)
        results["tpch_full"] = (tf if tf is not None
                                else "skipped (BENCH_TPCH_SF=0)")
    except AssertionError:
        raise   # a parity/compile-budget break IS a bench failure
    except Exception as e:   # noqa: BLE001 — report, don't fail bench
        if os.environ.get("BENCH_DEBUG"):
            raise
        results["tpch_full"] = {"error": str(e)[:300]}

    # --- optional: hand-fused pallas scan vs the XLA kernel -------------
    # (BENCH_PALLAS=1; the flag stays off otherwise so the driver's run
    # never depends on the pallas TPU compile)
    if os.environ.get("BENCH_PALLAS") == "1":
        flags.set_flag("tpu_pallas_scan", True)
        try:
            pk = ScanKernel()
            q = TPCH_Q6
            batch = build_batch(blocks, sorted(q.columns))

            def pallas_run():
                outs, counts, m = pk.run(batch, q.where, q.aggs, q.group)
                jax.block_until_ready(outs)
                return outs, counts, m
            _, _, m0 = pallas_run()
            pl_t, (pl_out, _, _) = best_of(pallas_run, repeats)
            ref = numpy_reference(q, data)
            rel = abs(float(pl_out[0]) - ref) / max(abs(ref), 1e-9)
            results["q6_pallas"] = {
                "routed": m0 is None, "rows_per_s": n / pl_t,
                "vs_xla": results["q6"]["tpu_s"] / pl_t,
                "rel_err": rel,
            }
        except Exception as e:   # noqa: BLE001 — report, don't fail bench
            results["q6_pallas"] = {"error": str(e)[:200]}
        finally:
            flags.set_flag("tpu_pallas_scan", False)

    # --- distributed Q1 (BASELINE config 3): 8 tablets ------------------
    dtable = LineitemTable(tempfile.mkdtemp(prefix="ybtpu-dist-"),
                           num_tablets=8)
    dtable.load(data)
    q1ref = numpy_reference(TPCH_Q1, data)
    if len(jax.devices()) >= 8:
        from yugabyte_db_tpu.parallel.distributed_scan import (
            build_sharded_batch, distributed_scan_aggregate,
        )
        from yugabyte_db_tpu.parallel.mesh import tablet_mesh
        tm = tablet_mesh(num_tablet_shards=8)
        shard_blocks = []
        for t in dtable.tablets:
            bl = []
            for r in t.regular.ssts:
                for i in range(r.num_blocks()):
                    bl.append(r.columnar_block(i))
            shard_blocks.append(bl)
        sbatch = build_sharded_batch(tm, shard_blocks,
                                     sorted(TPCH_Q1.columns))

        def dist_run():
            sums, counts = distributed_scan_aggregate(
                sbatch, TPCH_Q1.where, TPCH_Q1.aggs, TPCH_Q1.group)
            jax.block_until_ready(sums)
            return sums, counts
        dist_run()
        dist_t, (dsums, dcounts) = best_of(dist_run, repeats)
        check_q1([np.asarray(s) for s in dsums], np.asarray(dcounts), q1ref)
        combine = "psum"
    else:
        # single visible device: per-tablet kernels + host combine (the
        # single-chip execution of the same fan-out)
        def dist_run():
            return dtable.run(TPCH_Q1)
        dist_run()
        dist_t, (dsums, dcounts) = best_of(dist_run, max(2, repeats // 2))
        check_q1([np.asarray(s) for s in dsums], np.asarray(dcounts), q1ref)
        combine = "host"
    results["q1_dist"] = {"tablets": 8, "combine": combine,
                          "rows_per_s": n / dist_t, "seconds": dist_t}

    # --- compaction at spec (BASELINE config 4): N-SST major merge ------
    n_ssts = int(os.environ.get("BENCH_COMPACT_SSTS", "100"))
    rows_per = int(os.environ.get("BENCH_COMPACT_ROWS", "20000"))

    def timed_compaction_once(flag, tag):
        # the CPU side is the full PRE-PR configuration: monolithic
        # baseline engine AND sst_format_version=1 for both the input
        # tablet and the output, so vs_cpu measures the complete
        # engine+format upgrade and the cpu output doubles as the v1
        # byte yardstick for v2_vs_v1_bytes
        if not flag:
            flags.set_flag("sst_format_version", 1)
        try:
            ct = _make_compaction_tablet(data, n_ssts, rows_per, tag)
            nbytes = ct.approximate_size()
            flags.set_flag("tpu_compaction_enabled", flag)
            t0 = time.perf_counter()
            ct.compact()
            dt = time.perf_counter() - t0
        finally:
            if not flag:
                flags.REGISTRY.reset("sst_format_version")
        out = ct.regular.ssts[0]
        return dt, nbytes, out.file_size, out.num_entries

    # best-of-2 rounds, modes INTERLEAVED inside each round: the two
    # paths then see the same machine conditions (page cache, competing
    # load), so the ratio measures the engines rather than system drift;
    # round 0 additionally absorbs cold imports for both
    from yugabyte_db_tpu.docdb.compaction import LAST_COMPACTION_STATS
    dev_s = cpu_comp_s = None
    dev_in = cpu_in = dev_out = dev_rows = cpu_out = 0
    dev_pipeline = {}
    for i in range(2):
        d, dev_in, dev_out, dev_rows = \
            timed_compaction_once(True, f"dev{i}")
        if dev_s is None or d < dev_s:
            dev_pipeline = {k: (round(v, 4) if isinstance(v, float)
                                else v)
                            for k, v in LAST_COMPACTION_STATS.items()
                            if k != "lanes"}
        c, cpu_in, cpu_out, _ = timed_compaction_once(False, f"cpu{i}")
        dev_s = d if dev_s is None else min(dev_s, d)
        cpu_comp_s = c if cpu_comp_s is None else min(cpu_comp_s, c)
    flags.set_flag("tpu_compaction_enabled", True)
    lrb = _logical_row_bytes(table.info)
    results["compaction"] = {
        # input byte counts differ per world (the v2 inputs are ~3x
        # smaller on disk): each rate is computed over its own bytes
        "ssts": n_ssts, "input_mb": dev_in / 1e6,
        "cpu_input_mb": cpu_in / 1e6,
        "mb_per_s": dev_in / 1e6 / dev_s,
        "cpu_mb_per_s": cpu_in / 1e6 / cpu_comp_s,
        "vs_cpu": cpu_comp_s / dev_s,
        "seconds": dev_s,
        # output-byte surgery accounting: the baseline run writes the
        # pre-v2 format, so v2_vs_v1_bytes = v1 bytes / v2 bytes on
        # the SAME logical output (>= 1.0 means v2 is smaller)
        "output_rows": dev_rows,
        "output_bytes_per_row": round(dev_out / max(dev_rows, 1), 2),
        "v1_output_bytes_per_row": round(cpu_out / max(dev_rows, 1), 2),
        "v2_vs_v1_bytes": round(cpu_out / max(dev_out, 1), 3),
        "write_amp": round(dev_out / max(dev_rows * lrb, 1), 3),
        "write_wait_s": dev_pipeline.get("write_wait_s"),
        "pipeline": dev_pipeline,
    }

    # YCSB workload C (BASELINE config 1): engine-level point reads.
    # A short untimed run first: the first few thousand ops pay block-
    # cache warmup and would dominate a small timed run.
    from yugabyte_db_tpu.models.ycsb import YcsbTabletWorkload, usertable_info
    from yugabyte_db_tpu.tablet import Tablet
    yt = Tablet("ycsb", usertable_info(), tempfile.mkdtemp(prefix="ycsb-"))
    w = YcsbTabletWorkload(yt, n_rows=100_000)
    w.load()
    w.run("c", ops=2000)   # warm
    ycsb_ops = int(os.environ.get("BENCH_YCSB_OPS", "20000"))
    rc = w.run("c", ops=ycsb_ops)
    # 16 concurrent sessions batching at the server seam (the engine
    # analog of the reference's multi-threaded YCSB drivers; reference
    # number: 77K ops/s across 3 nodes, ycsb-ysql.md:188)
    rb = w.run("c", ops=ycsb_ops, clients=16)
    results["ycsb_c"] = {"ops_per_s": rc.ops_per_sec,
                         "batched16_ops_per_s": rb.ops_per_sec}
    # workloads A (50/50 read-update) and E (short scans) round out the
    # reference's YCSB table (ycsb-ysql.md:186,190)
    ra = w.run("a", ops=max(2000, ycsb_ops // 4))
    rb_ = w.run("b", ops=max(2000, ycsb_ops // 4))
    re_ = w.run("e", ops=max(1000, ycsb_ops // 10))
    results["ycsb_a"] = {"ops_per_s": ra.ops_per_sec}
    results["ycsb_b"] = {"ops_per_s": rb_.ops_per_sec}
    results["ycsb_e"] = {"ops_per_s": re_.ops_per_sec}

    # YCSB-C at 2x saturation through the RPC path: scheduler ON vs
    # OFF (admission control + micro-batching headline; BENCH_OVERLOAD_S
    # bounds each side, 0 skips)
    bp = bypass_scan_bench()
    if bp is not None:
        results["bypass_scan"] = bp

    # incremental matviews fed by the CDC stream under 2x write load:
    # staleness p99, write-lane p99 impact, and the incremental-vs-
    # full-rescan serve ratio (matview_vs_rescan WARNs below 1;
    # BENCH_MATVIEW_S=0 skips)
    mv = matview_bench()
    if mv is not None:
        results["matview"] = mv

    ol = ycsb_overload_bench()
    if ol is not None:
        results["ycsb_overload"] = ol

    # live fire on a REAL multi-process cluster: scheduler separation,
    # goodput through split+rebalance, seeded chaos with byte-verify,
    # bypass from a separate replica process (BENCH_CLUSTER_S bounds
    # each phase, 0 skips)
    co = cluster_overload_bench()
    if co is not None:
        results["cluster_overload"] = co

    # observability overhead gate: headline YCSB/Q6 rates through the
    # RPC path with tracing at default sampling vs off (BENCH_TRACE_S
    # bounds each round, 0 skips; ratios WARN below 0.98)
    tr = trace_overhead_bench()
    if tr is not None:
        results["trace_overhead"] = tr

    # TPC-C-style NEW-ORDER/PAYMENT through REAL distributed txns on an
    # in-process cluster (reference headline bench; tpmC here is the
    # UNCONSTRAINED NewOrder rate — no spec think times). BENCH_TPCC_S
    # bounds the run; 0 skips.
    tpcc_s = float(os.environ.get("BENCH_TPCC_S", "10"))
    if tpcc_s > 0:
        import asyncio as _aio
        from yugabyte_db_tpu.models.tpcc import TpccWorkload
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

        tpcc_wh = int(os.environ.get("BENCH_TPCC_WAREHOUSES", "1"))
        tpcc_terms = int(os.environ.get("BENCH_TPCC_TERMINALS", "8"))

        async def run_tpcc():
            mc = await MiniCluster(
                tempfile.mkdtemp(prefix="ybtpu-tpcc-"),
                num_tservers=1).start()
            try:
                c = mc.client()
                wload = TpccWorkload(c, warehouses=tpcc_wh)
                await wload.create_tables(num_tablets=1)
                for t_ in ("warehouse", "district", "customer", "stock",
                           "orders", "order_line", "history"):
                    await mc.wait_for_leaders(t_)
                await wload.load()
                await wload.run(seconds=2.0, concurrency=4)   # warm
                return await wload.run(seconds=tpcc_s,
                                       concurrency=tpcc_terms)
            finally:
                await mc.shutdown()
        try:
            tr = _aio.run(run_tpcc())
            import dataclasses as _dc
            # record the run CONFIGURATION next to the rates (VERDICT
            # item 9): tpmC without warehouse/terminal count is not a
            # comparable number
            results["tpcc"] = {**_dc.asdict(tr),
                               "warehouses": tpcc_wh,
                               "terminals": tpcc_terms,
                               "tpmc_unconstrained": tr.tpmc,
                               "abort_rate": tr.abort_rate}
        except Exception as e:   # noqa: BLE001 — report, don't fail bench
            results["tpcc"] = {"error": str(e)[:200]}

    # Vector search (BASELINE config 5): the reduced config plus the
    # full 1M x 768 spec config, through the vector/ subsystem's
    # two-stage IVF (multi-probe + GEMM re-rank).  Fine clustering is
    # the recall lever on isotropic data (the IVF worst case): r5's
    # flat IVF at nlists=200/nprobe=50 stalled at recall 0.744; the
    # two-stage engine at nlists=1024/nprobe=256 measures >=0.99 while
    # the blocked shared re-rank GEMM keeps qps above the old engine.
    # (BENCH_VECTOR_FULL=0 skips the big one)
    from yugabyte_db_tpu.vector import TwoStageIvfIndex

    def vector_bench(vn, vd, nlists, iters, repeats_v, nprobe=None):
        from yugabyte_db_tpu.ops.vector import exact_search
        rngv = np.random.default_rng(0)
        vbase = rngv.normal(size=(vn, vd)).astype(np.float32)
        t0 = time.perf_counter()
        idx = TwoStageIvfIndex.build(vbase, nlists=nlists, iters=iters,
                                     sample=50_000)
        build_s = time.perf_counter() - t0
        vq = vbase[:64] + 0.001
        np_ = nprobe or max(8, nlists // 4)
        idx.search(vq, k=10, nprobe=np_)   # warm/compile
        t0 = time.perf_counter()
        for _ in range(repeats_v):
            idx.search(vq, k=10, nprobe=np_)
        search_s = (time.perf_counter() - t0) / repeats_v
        # honesty: IVF search is approximate — report recall@10 vs an
        # exact scan on a query subsample so qps can't silently trade
        # away accuracy.  Same routing as the QPS loop: search the
        # FULL 64-query batch, compare a subsample.
        nq_r = 16
        _, ids = idx.search(vq, k=10, nprobe=np_)
        ids = ids[:nq_r]
        import jax.numpy as _jnp
        _, ref_ids = exact_search(_jnp.asarray(vq[:nq_r]),
                                  _jnp.asarray(vbase), 10)
        ref_ids = np.asarray(ref_ids)
        recall = float(np.mean([
            len(set(ids[i]) & set(ref_ids[i])) / 10.0
            for i in range(nq_r)]))
        from yugabyte_db_tpu.vector.ivf import kernel_cache_stats
        return {"n": vn, "dim": vd, "build_s": build_s,
                "nlists": int(idx.nlists), "nprobe": np_,
                "candidate_pool": int(idx.last_pool_rows),
                "ef": None,    # the HNSW twin's knob; IVF has none
                "kernel_cache": kernel_cache_stats(),
                "qps": 64 / search_s, "recall_at_10": recall}

    results["vector"] = vector_bench(200_000, 128, 256, 5, 5)
    if os.environ.get("BENCH_VECTOR_FULL", "1") != "0":
        results["vector_full"] = vector_bench(1_000_000, 768, 1024, 2, 2)

    # --- driver-conformance accounting (VERDICT r4 item 8) --------------
    # The external-driver suites (psycopg / cassandra-driver / redis-py)
    # need real drivers that cannot be installed in this image; a
    # pytest skip must never read as coverage, so the bench records
    # exactly which suites RAN (and their outcome) vs were SKIPPED and
    # why.  If a driver ever appears in the image, the suite runs here
    # automatically and its result replaces the skip entry.
    import subprocess as _sp
    driver_conf = {"ran": {}, "skipped": {}}
    _here = os.path.dirname(os.path.abspath(__file__))
    for mod, suite in (("psycopg", "tests/test_driver_conformance.py"),
                       ("cassandra", "tests/test_driver_conformance_cql.py"),
                       ("redis", "tests/test_driver_conformance_redis.py")):
        try:
            __import__(mod)
        except ImportError:
            # redis has a vendored fallback client (third_party/redispy,
            # an API-compatible RESP2 subset) which the suite imports
            # itself — that tier RUNS even without a system driver
            if not (mod == "redis" and os.path.isdir(os.path.join(
                    _here, "third_party", "redispy", "redis"))):
                driver_conf["skipped"][suite] = \
                    f"driver {mod!r} not installed"
                continue
        try:
            r = _sp.run([sys.executable, "-m", "pytest", suite, "-q",
                         "--no-header"],
                        capture_output=True, timeout=600,
                        cwd=os.path.dirname(os.path.abspath(__file__)))
            tail = (r.stdout or b"").decode("utf-8", "replace")
            tail = tail.strip().splitlines()[-1] if tail.strip() else ""
            driver_conf["ran"][suite] = {
                "passed": r.returncode == 0, "summary": tail[:120]}
        except Exception as e:   # noqa: BLE001 — account, don't fail bench
            driver_conf["ran"][suite] = {"passed": False,
                                         "summary": str(e)[:120]}

    q6 = results["q6"]
    line = {
        "metric": "tpch_q6_sf%g_tpu_rows_per_sec" % sf,
        "value": round(q6["tpu_rows_per_s"], 1),
        "unit": "rows/s",
        # best-of-N of the PER-ROUND ratio (kernel and baseline
        # interleaved back-to-back each round, so host contention
        # cancels); q6_paired carries the per-round ratios + raw times
        "vs_baseline": round(q6["speedup"], 3),
        "q6_paired": {"ratio_rounds": q6["ratio_rounds"],
                      "ratio_median": round(sorted(
                          q6["ratio_rounds"])[
                              len(q6["ratio_rounds"]) // 2], 3),
                      "tpu_s": round(q6["tpu_s"], 4),
                      "cpu_s": round(q6["cpu_s"], 4)},
        # RPC hot path vs SST-direct bypass on the same rows (ROADMAP
        # bypass item (e)); bypass_vs_hotpath WARN-wires like any ratio
        "q6_bypass": q6["bypass"],
        "device": str(dev) + (" (FALLBACK: accelerator unreachable)"
                              if device_fallback else ""),
        **({"device_probe_failures": probe_log} if device_fallback else {}),
        "rows": n,
        "load_rows_per_s": round(loaded / load_s, 1),
        "bulk_load": results["bulk_load"],
        # warm rates above; cold-scan split below (batch formation vs
        # kernel, streaming pipeline vs the r05 monolithic build)
        "cold_scan": results["cold_scan"],
        "q1": {"tpu_rows_per_s": round(results["q1"]["tpu_rows_per_s"], 1),
               "speedup": round(results["q1"]["speedup"], 3),
               "bypass": results["q1"]["bypass"]},
        # string-keyed Q1 through the streamed grouped kernel vs the
        # interpreted GROUP BY (+ cardinality sweep, CPU-twin oracle)
        "q1_grouped": results["q1_grouped"],
        # whole-query TPC-H gauntlet: the 22-query registry (runnable
        # adapted specs or typed inexpressible reasons); every
        # per-query fused_vs_interp in the subtree WARN-wires
        "tpch_full": results["tpch_full"],
        "doc_scan": results["doc_scan"],
        "q1_dist8": {
            "rows_per_s": round(results["q1_dist"]["rows_per_s"], 1),
            "combine": results["q1_dist"]["combine"]},
        "compaction": {
            "ssts": results["compaction"]["ssts"],
            "input_mb": round(results["compaction"]["input_mb"], 1),
            "mb_per_s": round(results["compaction"]["mb_per_s"], 2),
            "cpu_mb_per_s": round(results["compaction"]["cpu_mb_per_s"], 2),
            "vs_cpu": round(results["compaction"]["vs_cpu"], 3),
            "output_bytes_per_row":
                results["compaction"]["output_bytes_per_row"],
            "v1_output_bytes_per_row":
                results["compaction"]["v1_output_bytes_per_row"],
            "v2_vs_v1_bytes": results["compaction"]["v2_vs_v1_bytes"],
            "write_amp": results["compaction"]["write_amp"],
            "write_wait_s": results["compaction"]["write_wait_s"],
            "pipeline": results["compaction"]["pipeline"]},
        **({"q6_pallas": {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in results["q6_pallas"].items()}}
           if "q6_pallas" in results else {}),
        "ycsb_c_ops_per_s": round(results["ycsb_c"]["ops_per_s"], 1),
        "ycsb_c16_ops_per_s": round(
            results["ycsb_c"]["batched16_ops_per_s"], 1),
        "ycsb_a_ops_per_s": round(results["ycsb_a"]["ops_per_s"], 1),
        "ycsb_b_ops_per_s": round(results["ycsb_b"]["ops_per_s"], 1),
        **({"tpcc": {k: (round(v, 1) if isinstance(v, float) else v)
                     for k, v in results["tpcc"].items()}}
           if "tpcc" in results else {}),
        "ycsb_e_ops_per_s": round(results["ycsb_e"]["ops_per_s"], 1),
        **({"ycsb_overload": results["ycsb_overload"]}
           if "ycsb_overload" in results else {}),
        **({"cluster_overload": results["cluster_overload"]}
           if "cluster_overload" in results else {}),
        **({"trace_overhead": results["trace_overhead"]}
           if "trace_overhead" in results else {}),
        **({"bypass_scan": results["bypass_scan"]}
           if "bypass_scan" in results else {}),
        **({"matview": results["matview"]}
           if "matview" in results else {}),
        "driver_conformance": driver_conf,
        "vector": _vector_line(results["vector"]),
        **({"vector_full": _vector_line(results["vector_full"])}
           if "vector_full" in results else {}),
    }
    print(json.dumps(line))
    # regression visibility: any kernel-vs-baseline ratio below 1.0 (or
    # an overload p99 ratio the scheduler failed to hold) lands as a
    # WARN in the bench tail (stderr keeps the one-JSON-line stdout
    # contract) instead of hiding inside the blob
    for path, v in warn_regressed_ratios(line):
        print(f"WARN: ratio {path}={v} regressed past its threshold",
              file=sys.stderr)
    for msg in warn_suppression_growth():
        print(f"WARN: {msg}", file=sys.stderr)


if __name__ == "__main__":
    main()
