"""Profile vector search variants (throwaway)."""
import os, time
os.environ.setdefault("YBTPU_PLATFORM", "cpu")
import numpy as np
import jax, jax.numpy as jnp
from yugabyte_db_tpu.ops.vector import IvfFlatIndex, exact_search, l2_distance2

n, d = 200_000, 128
rng = np.random.default_rng(0)
base = rng.normal(size=(n, d)).astype(np.float32)
q = base[:64] + 0.001

t0 = time.perf_counter()
idx = IvfFlatIndex.build(base, nlists=64, iters=5)
print(f"build: {time.perf_counter()-t0:.2f}s")

idx.search(q, k=10, nprobe=8)
t0 = time.perf_counter()
for _ in range(5):
    idx.search(q, k=10, nprobe=8)
dt = (time.perf_counter() - t0) / 5
print(f"ivf search: {dt*1e3:.1f} ms/batch  {64/dt:.0f} qps")

bj = jnp.asarray(base)
qj = jnp.asarray(q)
jax.block_until_ready(exact_search(qj, bj, 10))
t0 = time.perf_counter()
for _ in range(5):
    jax.block_until_ready(exact_search(qj, bj, 10))
dt = (time.perf_counter() - t0) / 5
print(f"exact bf16: {dt*1e3:.1f} ms/batch  {64/dt:.0f} qps")

@jax.jit
def exact_f32(queries, base, k=10):
    dots = queries @ base.T
    qn = jnp.sum(queries ** 2, axis=1, keepdims=True)
    bn = jnp.sum(base ** 2, axis=1)
    dist = qn + bn[None, :] - 2.0 * dots
    neg, i = jax.lax.top_k(-dist, 10)
    return -neg, i

jax.block_until_ready(exact_f32(qj, bj))
t0 = time.perf_counter()
for _ in range(5):
    jax.block_until_ready(exact_f32(qj, bj))
dt = (time.perf_counter() - t0) / 5
print(f"exact f32: {dt*1e3:.1f} ms/batch  {64/dt:.0f} qps")

# numpy BLAS reference
t0 = time.perf_counter()
for _ in range(5):
    dots = q @ base.T
    dist = (q**2).sum(1)[:, None] + (base**2).sum(1)[None, :] - 2*dots
    part = np.argpartition(dist, 10, axis=1)[:, :10]
dt = (time.perf_counter() - t0) / 5
print(f"numpy f32: {dt*1e3:.1f} ms/batch  {64/dt:.0f} qps")

# new routed search
idx2 = IvfFlatIndex.build(base, nlists=64, iters=5)
dd, ii = idx2.search(q, k=10, nprobe=8)
de, ie = exact_search(qj, bj, 10)
print("routed==exact idx match:", float((ii == np.asarray(ie)).mean()))
t0 = time.perf_counter()
for _ in range(5):
    idx2.search(q, k=10, nprobe=8)
dt = (time.perf_counter() - t0) / 5
print(f"routed search: {dt*1e3:.1f} ms/batch  {64/dt:.0f} qps")
# small batch keeps gather path
d1, i1 = idx2.search(q[:2], k=10, nprobe=8)
print("small-batch ok:", d1.shape, i1.shape)
