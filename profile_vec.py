"""Profile the vector/ subsystem: recall/qps frontier sweeps.

--json: one JSON object on stdout (mirroring profile_compact.py) with
  * an IVF nprobe x rerank_c sweep (CPU twin + device-kernel bucket)
    emitting the recall/qps frontier at the profiled scale,
  * an HNSW ef_search sweep at a host-friendly scale,
  * kernel-compile accounting for the jitted two-stage path (same
    contract as the compaction kernels: pow2 buckets compile once).

Env knobs: PROF_VEC_N (default 200000), PROF_VEC_D (128),
PROF_VEC_LISTS (256), PROF_VEC_HNSW_N (20000), PROF_VEC_REPEATS (3).
"""
import json
import os
import sys
import time

os.environ.setdefault("YBTPU_PLATFORM", "cpu")

import numpy as np   # noqa: E402

from yugabyte_db_tpu.ops.vector import exact_search   # noqa: E402
from yugabyte_db_tpu.vector import (                  # noqa: E402
    HnswIndex, TwoStageIvfIndex,
)
from yugabyte_db_tpu.vector.ivf import (              # noqa: E402
    kernel_cache_stats, reset_kernel_stats,
)

as_json = "--json" in sys.argv
n = int(os.environ.get("PROF_VEC_N", "200000"))
d = int(os.environ.get("PROF_VEC_D", "128"))
nlists = int(os.environ.get("PROF_VEC_LISTS", "256"))
hnsw_n = int(os.environ.get("PROF_VEC_HNSW_N", "20000"))
repeats = int(os.environ.get("PROF_VEC_REPEATS", "3"))

rng = np.random.default_rng(0)
base = rng.normal(size=(n, d)).astype(np.float32)
q = base[:64] + 0.001

import jax.numpy as jnp   # noqa: E402

_, ref_ids = exact_search(jnp.asarray(q[:16]), jnp.asarray(base), 10)
ref_ids = np.asarray(ref_ids)


def recall_of(ids):
    return float(np.mean([
        len(set(ids[i]) & set(ref_ids[i])) / 10.0 for i in range(16)]))


def timed(fn):
    fn()                      # warm / compile
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = fn()
        t0_last = out
    return (time.perf_counter() - t0) / repeats, t0_last


out = {"n": n, "dim": d, "nlists": nlists, "queries": 64}

t0 = time.perf_counter()
ivf = TwoStageIvfIndex.build(base, nlists=nlists, iters=5,
                             sample=50_000)
out["ivf_build_s"] = round(time.perf_counter() - t0, 2)

# ---- IVF frontier: nprobe x rerank_c ---------------------------------
frontier = []
for nprobe in (max(1, nlists // 16), max(1, nlists // 8),
               max(1, nlists // 4), max(1, nlists // 2)):
    dt, (_, ids) = timed(lambda: ivf.search(q, k=10, nprobe=nprobe))
    frontier.append({"backend": "cpu", "nprobe": nprobe,
                     "candidate_pool": int(ivf.last_pool_rows),
                     "qps": round(64 / dt, 1),
                     "recall_at_10": round(recall_of(ids), 3)})
reset_kernel_stats()
for nprobe in (max(1, nlists // 8), max(1, nlists // 4)):
    for rerank_c in (64, 256):
        dt, (_, ids) = timed(lambda: ivf.search(
            q, k=10, nprobe=nprobe, rerank_c=rerank_c,
            backend="device"))
        frontier.append({"backend": "device-kernel", "nprobe": nprobe,
                         "rerank_c": rerank_c,
                         "candidate_pool": int(ivf.last_pool_rows),
                         "qps": round(64 / dt, 1),
                         "recall_at_10": round(recall_of(ids), 3)})
out["ivf_frontier"] = frontier
# shape-stable buckets: the 4 (nprobe, rerank_c) points above compile
# once each; the repeat calls inside timed() must all be cache hits
out["ivf_kernel_cache"] = kernel_cache_stats()

# ---- HNSW frontier: ef_search ----------------------------------------
hq = base[:64] + 0.001
_, href = exact_search(jnp.asarray(hq[:16]), jnp.asarray(base[:hnsw_n]),
                       10)
href = np.asarray(href)
t0 = time.perf_counter()
hnsw = HnswIndex.build(base[:hnsw_n], m=16, ef_construction=80)
out["hnsw_build_s"] = round(time.perf_counter() - t0, 2)
out["hnsw_n"] = hnsw_n
hfrontier = []
for ef in (16, 32, 64, 128):
    dt, (_, ids) = timed(lambda: hnsw.search(hq, k=10, ef_search=ef))
    hfrontier.append({"ef_search": ef, "qps": round(64 / dt, 1),
                      "recall_at_10": round(float(np.mean(
                          [len(set(ids[i]) & set(href[i])) / 10.0
                           for i in range(16)])), 3)})
out["hnsw_frontier"] = hfrontier

if as_json:
    print(json.dumps(out))
else:
    print(f"n={n} d={d} nlists={nlists} "
          f"(ivf build {out['ivf_build_s']}s, "
          f"hnsw build {out['hnsw_build_s']}s @ n={hnsw_n})")
    for f in frontier:
        extra = (f" c={f['rerank_c']}" if "rerank_c" in f else "")
        print(f"  ivf[{f['backend']}] nprobe={f['nprobe']}{extra}: "
              f"{f['qps']} qps recall={f['recall_at_10']} "
              f"pool={f['candidate_pool']}")
    print(f"  kernel cache: {out['ivf_kernel_cache']}")
    for f in hfrontier:
        print(f"  hnsw ef={f['ef_search']}: {f['qps']} qps "
              f"recall={f['recall_at_10']}")
