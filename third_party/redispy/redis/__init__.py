"""Vendored minimal redis client (redis-py API subset).

The image cannot reach PyPI, so instead of the full redis-py tree this
vendors a from-scratch RESP2 client exposing the exact ``redis.Redis``
surface the conformance suites drive (connect / ping / strings /
counters / hashes / lists / sets / delete / generic execute_command).
Protocol framing follows the RESP2 spec (inline with redis-py 5.x
semantics: byte responses, bool for PING/SISMEMBER, int for
INCR/DEL/RPUSH).  If a real redis-py ever appears on sys.path it wins
— the test harness only falls back here on ImportError.
"""
from __future__ import annotations

import socket
from typing import List, Optional, Union

__version__ = "0.1-vendored-resp2"


class RedisError(Exception):
    pass


class ConnectionError(RedisError):   # noqa: A001 — redis-py name
    pass


class ResponseError(RedisError):
    pass


def _encode(arg) -> bytes:
    if isinstance(arg, bytes):
        return arg
    if isinstance(arg, (int, float)):
        arg = repr(arg) if isinstance(arg, float) else str(arg)
    return str(arg).encode("utf-8")


class Redis:
    """Subset of redis-py's client: one blocking connection, RESP2."""

    def __init__(self, host: str = "localhost", port: int = 6379,
                 db: int = 0, socket_timeout: Optional[float] = None,
                 decode_responses: bool = False, **_ignored):
        self.host, self.port = host, int(port)
        self.db = db
        self.socket_timeout = socket_timeout
        self.decode_responses = decode_responses
        self._sock: Optional[socket.socket] = None
        self._buf = b""

    # ---- connection ------------------------------------------------------
    def _connect(self) -> socket.socket:
        if self._sock is None:
            try:
                s = socket.create_connection(
                    (self.host, self.port), timeout=self.socket_timeout)
            except OSError as e:
                raise ConnectionError(str(e)) from e
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
            if self.db:
                self.execute_command("SELECT", self.db)
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._buf = b""

    # ---- RESP2 framing ---------------------------------------------------
    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                self.close()
                raise ConnectionError("connection closed by server")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                self.close()
                raise ConnectionError("connection closed by server")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n + 2:]
        return out

    def _read_reply(self):
        line = self._read_line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest
        if t == b"-":
            raise ResponseError(rest.decode("utf-8", "replace"))
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            return None if n < 0 else self._read_exact(n)
        if t == b"*":
            n = int(rest)
            return (None if n < 0
                    else [self._read_reply() for _ in range(n)])
        raise ResponseError(f"unknown RESP type {line!r}")

    def execute_command(self, *args):
        s = self._connect()
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            e = _encode(a)
            out.append(b"$%d\r\n%s\r\n" % (len(e), e))
        try:
            s.sendall(b"".join(out))
            reply = self._read_reply()
        except (OSError, socket.timeout) as e:
            self.close()
            raise ConnectionError(str(e)) from e
        if self.decode_responses:
            reply = self._decode(reply)
        return reply

    def _decode(self, r):
        if isinstance(r, bytes):
            return r.decode("utf-8", "replace")
        if isinstance(r, list):
            return [self._decode(x) for x in r]
        return r

    # ---- commands (redis-py return conventions) --------------------------
    def ping(self) -> bool:
        r = self.execute_command("PING")
        return r in (b"PONG", "PONG", True)

    def set(self, name, value, ex: Optional[int] = None,
            px: Optional[int] = None) -> bool:
        args: List[Union[bytes, str, int]] = ["SET", name, value]
        if ex is not None:
            args += ["EX", ex]
        if px is not None:
            args += ["PX", px]
        return self.execute_command(*args) in (b"OK", "OK")

    def get(self, name):
        return self.execute_command("GET", name)

    def delete(self, *names) -> int:
        return self.execute_command("DEL", *names)

    def exists(self, *names) -> int:
        return self.execute_command("EXISTS", *names)

    def incr(self, name, amount: int = 1) -> int:
        if amount == 1:
            return self.execute_command("INCR", name)
        return self.execute_command("INCRBY", name, amount)

    def decr(self, name, amount: int = 1) -> int:
        return self.execute_command("DECRBY", name, amount)

    def append(self, name, value) -> int:
        return self.execute_command("APPEND", name, value)

    def strlen(self, name) -> int:
        return self.execute_command("STRLEN", name)

    def expire(self, name, seconds: int) -> int:
        return self.execute_command("EXPIRE", name, seconds)

    def ttl(self, name) -> int:
        return self.execute_command("TTL", name)

    # hashes
    def hset(self, name, key=None, value=None, mapping=None) -> int:
        args = ["HSET", name]
        if key is not None:
            args += [key, value]
        for k, v in (mapping or {}).items():
            args += [k, v]
        return self.execute_command(*args)

    def hget(self, name, key):
        return self.execute_command("HGET", name, key)

    def hdel(self, name, *keys) -> int:
        return self.execute_command("HDEL", name, *keys)

    def hgetall(self, name) -> dict:
        flat = self.execute_command("HGETALL", name) or []
        return dict(zip(flat[::2], flat[1::2]))

    # lists
    def rpush(self, name, *values) -> int:
        return self.execute_command("RPUSH", name, *values)

    def lpush(self, name, *values) -> int:
        return self.execute_command("LPUSH", name, *values)

    def lrange(self, name, start: int, end: int) -> list:
        return self.execute_command("LRANGE", name, start, end) or []

    def llen(self, name) -> int:
        return self.execute_command("LLEN", name)

    def lpop(self, name):
        return self.execute_command("LPOP", name)

    def rpop(self, name):
        return self.execute_command("RPOP", name)

    # sets
    def sadd(self, name, *values) -> int:
        return self.execute_command("SADD", name, *values)

    def srem(self, name, *values) -> int:
        return self.execute_command("SREM", name, *values)

    def sismember(self, name, value) -> bool:
        return bool(self.execute_command("SISMEMBER", name, value))

    def smembers(self, name) -> set:
        return set(self.execute_command("SMEMBERS", name) or [])

    def scard(self, name) -> int:
        return self.execute_command("SCARD", name)


StrictRedis = Redis
