"""Stage profile of the incremental-matview maintainer (matview/).

One registered view (count + sum + min/max over a 16-group INT64 key)
seeds from a pinned read point, then folds a churn batch of updates
and extremum deletes off the CDC stream. The maintainer's wall clock
splits into the stages ViewMaintainer.stage_s accumulates:

  seed    - slot creation + watermark pin + the ONE grouped seed scan
  stream  - VirtualWal.get_consistent_changes (change-record drain)
  fold    - txn apply: before-image point reads, combine + retract
  rescan  - bounded per-group MIN/MAX repair scans after retraction
  persist - catalog checkpoint writes + confirm_flush

alongside the retraction/re-scan counters (rows_added, rows_retracted,
before_image_reads, minmax_rescans, budget_exceeded, full_rescans) and
a timed REFRESH (the full-rescan escape hatch) for contrast. Parity is
asserted inside: the folded view must bit-match a host fold of a full
scan at the view's watermark.

Usage:
  python profile_matview.py --json

Env knobs: PROFILE_MV_ROWS (base-table rows, default 20000),
PROFILE_MV_CHURN (churn ops folded through the stream, default 2000).
"""
import json
import os
import sys
import time

os.environ.setdefault("YBTPU_PLATFORM", "cpu")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_GROUPS = 16


def profile_json() -> dict:
    import asyncio
    import tempfile

    import numpy as np

    from yugabyte_db_tpu.docdb.table_codec import TableInfo
    from yugabyte_db_tpu.dockv.packed_row import (ColumnSchema, ColumnType,
                                                  TableSchema)
    from yugabyte_db_tpu.dockv.partition import PartitionSchema
    from yugabyte_db_tpu.matview import ViewDef
    from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

    n_rows = int(os.environ.get("PROFILE_MV_ROWS", "20000"))
    n_churn = int(os.environ.get("PROFILE_MV_CHURN", "2000"))

    schema = TableSchema(columns=(
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "g", ColumnType.INT64),
        ColumnSchema(2, "v", ColumnType.INT64),
    ), version=1)
    info = TableInfo("", "kv", schema, PartitionSchema("hash", 1))

    async def run() -> dict:
        mc = await MiniCluster(tempfile.mkdtemp(prefix="ybtpu-mvprof-"),
                               num_tservers=1).start()
        try:
            c = mc.client()
            await c.create_table(info, num_tablets=1,
                                 replication_factor=1)
            await mc.wait_for_leaders("kv")

            rng = np.random.default_rng(23)
            vals = {}
            t0 = time.perf_counter()
            for lo in range(0, n_rows, 2000):
                batch = [{"k": i, "g": i % N_GROUPS,
                          "v": int(rng.integers(0, 1 << 20))}
                         for i in range(lo, min(lo + 2000, n_rows))]
                for r in batch:
                    vals[r["k"]] = r["v"]
                await c.insert("kv", batch)
            load_s = time.perf_counter() - t0

            vd = ViewDef("mv_prof", "kv", "", ["g"],
                         [("count", None, "cnt"),
                          ("sum", ("col", "v"), "total"),
                          ("min", ("col", "v"), "lo"),
                          ("max", ("col", "v"), "hi")])
            t0 = time.perf_counter()
            mt = await c.matviews().create(vd)
            create_s = time.perf_counter() - t0

            # churn: updates of existing keys (each one an add + a
            # retract through the fold), plus deletes of four groups'
            # current maxima — guaranteed dirty MIN/MAX slots, so the
            # rescan stage is exercised under the default budget
            t0 = time.perf_counter()
            ks = rng.integers(0, n_rows, size=n_churn)
            for lo in range(0, n_churn, 500):
                batch = [{"k": int(k), "g": int(k) % N_GROUPS,
                          "v": int(rng.integers(0, 1 << 20))}
                         for k in ks[lo:lo + 500]]
                for r in batch:
                    vals[r["k"]] = r["v"]
                await c.insert("kv", batch)
            doomed = []
            for g in range(4):
                gk = max((k for k in vals if k % N_GROUPS == g),
                         key=vals.__getitem__)
                doomed.append({"k": gk})
            await c.delete("kv", doomed)
            churn_s = time.perf_counter() - t0

            # drain the whole backlog to the freshest watermark; the
            # stage split below covers seed + every fold round
            t0 = time.perf_counter()
            rows, meta = await c.matviews().read_rows(
                "mv_prof", max_staleness_ms=0.0)
            catch_up_s = time.perf_counter() - t0

            # parity gate: host fold of a full scan at the view's
            # watermark must bit-match the maintained partials
            from yugabyte_db_tpu.docdb.operations import ReadRequest
            resp = await c.scan(
                "kv", ReadRequest("", read_ht=mt.watermark_ht))
            ref = {}
            for r in resp.rows:
                cnt, tot, lo_, hi = ref.get(
                    r["g"], (0, 0, None, None))
                ref[r["g"]] = (
                    cnt + 1, tot + r["v"],
                    r["v"] if lo_ is None else min(lo_, r["v"]),
                    r["v"] if hi is None else max(hi, r["v"]))
            got = {r["g"]: (int(r["cnt"]), int(r["total"]),
                            int(r["lo"]), int(r["hi"])) for r in rows}
            assert got == ref, "matview fold diverged from host fold"

            st = dict(mt.counters)
            assert st["minmax_rescans"] >= 1, \
                "extremum deletes produced no rescans"
            assert st["rows_retracted"] >= int(n_churn * 0.9), \
                "update churn produced no retractions"
            # capture the split before REFRESH re-enters the seed stage
            stages = {k: round(v, 6) for k, v in mt.stage_s.items()}

            # the escape hatch, timed for contrast with the fold
            t0 = time.perf_counter()
            await c.matviews().refresh("mv_prof")
            refresh_s = time.perf_counter() - t0
            return {
                "rows": n_rows,
                "churn_ops": n_churn + len(doomed),
                "groups": N_GROUPS,
                "load_s": round(load_s, 3),
                "create_s": round(create_s, 3),
                "churn_write_s": round(churn_s, 3),
                "catch_up_s": round(catch_up_s, 3),
                "refresh_s": round(refresh_s, 3),
                "stage_s": stages,
                "seed_route": st["seed_route"],
                "staleness_ms": round(meta["staleness_ms"], 3),
                "counters": {k: st[k] for k in (
                    "seeds", "txns_applied", "rows_added",
                    "rows_retracted", "before_image_reads",
                    "minmax_rescans", "budget_exceeded",
                    "full_rescans")},
            }
        finally:
            try:
                await c.matviews().stop()
            except Exception:
                pass
            await mc.shutdown()

    return asyncio.run(run())


def main() -> None:
    if "--json" in sys.argv:
        print(json.dumps(profile_json()))
        return
    sys.stderr.write(__doc__ + "\n")
    sys.exit(2)


if __name__ == "__main__":
    main()
