"""Profile the analytics bypass engine's stage split.

`--json` prints ONE JSON object breaking a bypass Q6/Q1 scan into its
stages — pin (flush + lease), block collection, prefilter, batch
formation, kernel dispatch, combine — plus the keyless-scan counters
(key_rebuilds MUST stay 0), the prefilter selectivity split, a
prefilter ON/OFF and chunk-size sweep so the near-data filter's win
and the chunk plan are tunable from data, and a grouped-scan stage
split (q1_grouped: dict-merge / build / kernel / combine wall, slot
occupancy, compile counts for the dict-key GROUP BY route).

Env knobs: PROFILE_SF (default 0.1), PROFILE_ROUNDS (default 3),
PROFILE_CHUNK_SWEEP (comma list of chunk_rows; default
"262144,1048576").
"""
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("YBTPU_PLATFORM", "cpu")
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def profile_json() -> dict:
    import numpy as np

    from yugabyte_db_tpu.bypass import BypassSession, pin_tablet
    from yugabyte_db_tpu.bypass.prefilter import LAST_PREFILTER_STATS
    from yugabyte_db_tpu.bypass.scan import (collect_keyless_blocks,
                                             open_snapshot_readers)
    from yugabyte_db_tpu.models.tpch import (TPCH_Q1, TPCH_Q6,
                                             generate_lineitem,
                                             lineitem_range_info,
                                             numpy_reference)
    from yugabyte_db_tpu.ops.stream_scan import LAST_STREAM_STATS
    from yugabyte_db_tpu.storage import native_lib
    from yugabyte_db_tpu.storage.columnar import KEY_REBUILD_STATS
    from yugabyte_db_tpu.tablet import Tablet

    sf = float(os.environ.get("PROFILE_SF", "0.1"))
    rounds = int(os.environ.get("PROFILE_ROUNDS", "3"))
    sweep = [int(x) for x in os.environ.get(
        "PROFILE_CHUNK_SWEEP", "262144,1048576").split(",") if x]

    data = generate_lineitem(sf)
    n = len(data["rowid"])
    t = Tablet("li-prof", lineitem_range_info(),
               tempfile.mkdtemp(prefix="bypass-prof-"))
    t.bulk_load(data, block_rows=65536)

    # stage split measured once, un-warmed (the cold path IS the
    # product: a session is one-shot by design)
    t0 = time.perf_counter()
    snap = pin_tablet(t)
    pin_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    readers = open_snapshot_readers(snap)
    blocks, bstats = collect_keyless_blocks(readers)
    collect_s = time.perf_counter() - t0
    snap.close()

    out = {
        "rows": n, "sf": sf,
        "native_prefilter": native_lib.available(),
        "pin_s": round(pin_s, 4),
        "collect_blocks_s": round(collect_s, 4),
        "blocks": bstats["blocks"],
        "keyless_blocks": bstats["keyless_blocks"],
        "queries": {},
    }

    for q, name in ((TPCH_Q6, "q6"), (TPCH_Q1, "q1")):
        ref = numpy_reference(q, data)
        modes = {}
        for tag, pf in (("prefilter_on", True), ("prefilter_off", False)):
            r0 = KEY_REBUILD_STATS["rebuilds"]
            with BypassSession([t], prefilter=pf) as s:
                best = None
                for _ in range(rounds):
                    t0 = time.perf_counter()
                    outs, counts, stats = s.scan_aggregate(
                        q.where, q.aggs, q.group)
                    wall = time.perf_counter() - t0
                    if best is None or wall < best[0]:
                        best = (wall, stats, dict(LAST_STREAM_STATS))
            wall, stats, stream = best
            if name == "q6":
                rel = abs(float(outs[0]) - ref) / max(abs(ref), 1e-9)
                assert rel < 1e-5, f"q6 mismatch {rel}"
            modes[tag] = {
                "wall_s": round(wall, 4),
                "rows_per_s": round(n / wall, 1),
                "path": stats.get("paths"),
                "key_rebuilds": KEY_REBUILD_STATS["rebuilds"] - r0,
                "build_s": stream.get("build_s"),
                "kernel_s": stream.get("kernel_s"),
                "consumer_wait_s": stream.get("consumer_wait_s"),
                "zone_blocks_pruned": stream.get("zone_blocks_pruned"),
                "prefilter_rows_in": stats.get("prefilter_rows_in", 0),
                "prefilter_rows_kept": stats.get("prefilter_rows_kept",
                                                 0),
                "prefilter_blocks_compacted":
                    LAST_PREFILTER_STATS["blocks_compacted"] if pf
                    else 0,
            }
        pin = modes["prefilter_on"]
        off = modes["prefilter_off"]
        modes["prefilter_speedup"] = round(
            off["wall_s"] / max(pin["wall_s"], 1e-9), 3)
        out["queries"][name] = modes

    # --- grouped-scan stage split: dict-key GROUP BY via bypass --------
    # Q1 over the string-keyed lineitem (dict-grouped kernel, keyless):
    # dict-merge / batch-build / kernel / cross-shard combine wall per
    # stage, slot occupancy, and the shared kernel's compile counter —
    # the knobs behind grouped_max_slots and streaming_chunk_rows.
    from yugabyte_db_tpu.docdb.operations import _SHARED_KERNEL
    from yugabyte_db_tpu.models.tpch import (lineitem_str_data,
                                             lineitem_str_info,
                                             tpch_q1_str)
    from yugabyte_db_tpu.ops.grouped_scan import (GROUPED_STATS,
                                                  LAST_GROUPED_STATS)
    st = Tablet("li-prof-s", lineitem_str_info(),
                tempfile.mkdtemp(prefix="bypass-prof-s-"))
    st.bulk_load(lineitem_str_data(data), block_rows=65536)
    q1g = tpch_q1_str()
    ref_g = numpy_reference(q1g, data)
    c0 = _SHARED_KERNEL.compiles
    l0 = GROUPED_STATS["launches"]
    r0 = KEY_REBUILD_STATS["rebuilds"]
    with BypassSession([st], chunk_rows=65536) as s:
        best = None
        for _ in range(rounds):
            gout: dict = {}
            t0 = time.perf_counter()
            gouts, gcounts, gstats = s.scan_aggregate(
                q1g.where, q1g.aggs, q1g.group, grouped_out=gout)
            wall = time.perf_counter() - t0
            if best is None or wall < best[0]:
                best = (wall, gstats, dict(LAST_GROUPED_STATS),
                        dict(LAST_STREAM_STATS))
    wall, gstats, grouped, stream = best
    counts = np.asarray(gcounts)
    for g in range(len(counts)):
        key = tuple(str(v[g]) for v in gout["group_values"])
        assert int(counts[g]) == ref_g[key][2], f"q1_grouped {key}"
    out["q1_grouped"] = {
        "wall_s": round(wall, 4),
        "rows_per_s": round(n / wall, 1),
        "path": gstats.get("paths"),
        "dict_merge_s": grouped.get("dict_merge_s"),
        "build_s": stream.get("build_s"),
        "kernel_s": grouped.get("kernel_s"),
        "combine_s": gstats.get("combine_s"),
        "num_slots": grouped.get("num_slots"),
        "slots_occupied": grouped.get("slots_occupied"),
        "spilled_rows": grouped.get("spilled_rows"),
        "kernel_launches": GROUPED_STATS["launches"] - l0,
        "kernel_compiles": _SHARED_KERNEL.compiles - c0,
        "key_rebuilds": KEY_REBUILD_STATS["rebuilds"] - r0,
    }

    chunk_sweep = {}
    for cr in sweep:
        with BypassSession([t], chunk_rows=cr, min_chunks=1) as s:
            s.scan_aggregate(TPCH_Q6.where, TPCH_Q6.aggs, None)  # warm
            best = None
            for _ in range(rounds):
                t0 = time.perf_counter()
                s.scan_aggregate(TPCH_Q6.where, TPCH_Q6.aggs, None)
                wall = time.perf_counter() - t0
                best = wall if best is None else min(best, wall)
        chunk_sweep[str(cr)] = {
            "wall_s": round(best, 4),
            "rows_per_s": round(n / best, 1),
            "chunks": LAST_STREAM_STATS.get("chunks"),
            "bucket_rows": LAST_STREAM_STATS.get("bucket_rows"),
        }
    out["q6_chunk_sweep"] = chunk_sweep
    out["gather_stats"] = dict(native_lib.GATHER_STATS)
    out["prefilter_calls"] = dict(native_lib.PREFILTER_STATS)
    return out


def main():
    if "--json" in sys.argv:
        print(json.dumps(profile_json()))
        return
    print("usage: profile_bypass.py --json", file=sys.stderr)
    sys.exit(2)


if __name__ == "__main__":
    main()
