// Native host-side hot paths for the storage engine.
//
// The reference implements its entire storage layer in C++ (reference:
// src/yb/rocksdb/, src/yb/util/ — block building, bloom filters, hashing,
// the merging iterator). Our TPU engine keeps bulk work vectorized in
// numpy/XLA, but four host paths remain per-row and hot:
//   - FNV-1a hashing of variable-length keys (bloom + device dedup ids)
//   - KV block encode/decode (shared-prefix compression, varint framing)
//   - bloom filter build/probe
//   - k-way merge of sorted runs (CPU compaction fallback, point reads)
// This library implements them in C++ with a C ABI consumed via ctypes
// (no pybind11 in the image). Python fallbacks remain for portability;
// tests exercise both.
//
// Build: see native/build.sh (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstring>
#include <queue>
#include <vector>

extern "C" {

// --------------------------------------------------------------------------
// FNV-1a 64-bit over variable-length rows.
// keys: concatenated bytes; offsets: n+1 u64 boundaries; out: n u64 hashes.
// --------------------------------------------------------------------------
void fnv64_batch(const uint8_t* keys, const uint64_t* offsets, int64_t n,
                 uint64_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h = 0xCBF29CE484222325ULL;
        for (uint64_t p = offsets[i]; p < offsets[i + 1]; ++p) {
            h = (h ^ keys[p]) * 0x100000001B3ULL;
        }
        out[i] = h;
    }
}

// --------------------------------------------------------------------------
// Varint helpers
// --------------------------------------------------------------------------
static inline size_t put_uvarint(uint8_t* dst, uint64_t v) {
    size_t i = 0;
    while (v >= 0x80) {
        dst[i++] = (uint8_t)(v) | 0x80;
        v >>= 7;
    }
    dst[i++] = (uint8_t)v;
    return i;
}

static inline uint64_t get_uvarint(const uint8_t* src, size_t* pos) {
    uint64_t v = 0;
    int shift = 0;
    for (;;) {
        uint8_t b = src[(*pos)++];
        v |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) return v;
        shift += 7;
    }
}

// --------------------------------------------------------------------------
// KV block encode: shared-prefix compressed entries.
// Inputs: concatenated keys/values + offsets (n+1 each).
// Output buffer must be large enough (use block_encode_bound).
// Returns encoded size.
// Layout: u32 count, then per entry: uvarint shared, uvarint unshared,
// uvarint vlen, key suffix, value. (Matches storage/sst.py::_encode_block.)
// --------------------------------------------------------------------------
int64_t block_encode_bound(const uint64_t* koff, const uint64_t* voff,
                           int64_t n) {
    return 4 + (int64_t)(koff[n] + voff[n]) + n * 30;
}

int64_t block_encode(const uint8_t* keys, const uint64_t* koff,
                     const uint8_t* vals, const uint64_t* voff,
                     int64_t n, uint8_t* out) {
    size_t pos = 0;
    out[pos++] = (uint8_t)(n & 0xFF);
    out[pos++] = (uint8_t)((n >> 8) & 0xFF);
    out[pos++] = (uint8_t)((n >> 16) & 0xFF);
    out[pos++] = (uint8_t)((n >> 24) & 0xFF);
    const uint8_t* prev = nullptr;
    size_t prev_len = 0;
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* k = keys + koff[i];
        size_t klen = koff[i + 1] - koff[i];
        size_t shared = 0;
        size_t lim = prev_len < klen ? prev_len : klen;
        while (shared < lim && prev[shared] == k[shared]) ++shared;
        size_t vlen = voff[i + 1] - voff[i];
        pos += put_uvarint(out + pos, shared);
        pos += put_uvarint(out + pos, klen - shared);
        pos += put_uvarint(out + pos, vlen);
        memcpy(out + pos, k + shared, klen - shared);
        pos += klen - shared;
        memcpy(out + pos, vals + voff[i], vlen);
        pos += vlen;
        prev = k;
        prev_len = klen;
    }
    return (int64_t)pos;
}

// --------------------------------------------------------------------------
// KV block decode: emits concatenated keys/values + offsets.
// Caller sizes outputs via block_decode_sizes (returns n, total key bytes,
// total value bytes).
// --------------------------------------------------------------------------
void block_decode_sizes(const uint8_t* data, int64_t len, int64_t* out_n,
                        int64_t* out_kbytes, int64_t* out_vbytes) {
    size_t pos = 0;
    uint32_t n = data[0] | (data[1] << 8) | (data[2] << 16) |
                 ((uint32_t)data[3] << 24);
    pos = 4;
    size_t kb = 0, vb = 0;
    size_t prev_klen = 0;
    for (uint32_t i = 0; i < n; ++i) {
        uint64_t shared = get_uvarint(data, &pos);
        uint64_t unshared = get_uvarint(data, &pos);
        uint64_t vlen = get_uvarint(data, &pos);
        prev_klen = shared + unshared;
        kb += prev_klen;
        vb += vlen;
        pos += unshared + vlen;
    }
    *out_n = n;
    *out_kbytes = (int64_t)kb;
    *out_vbytes = (int64_t)vb;
    (void)len;
}

void block_decode(const uint8_t* data, int64_t len, uint8_t* keys,
                  uint64_t* koff, uint8_t* vals, uint64_t* voff) {
    size_t pos = 0;
    uint32_t n = data[0] | (data[1] << 8) | (data[2] << 16) |
                 ((uint32_t)data[3] << 24);
    pos = 4;
    size_t kpos = 0, vpos = 0;
    koff[0] = 0;
    voff[0] = 0;
    const uint8_t* prev_key = nullptr;
    for (uint32_t i = 0; i < n; ++i) {
        uint64_t shared = get_uvarint(data, &pos);
        uint64_t unshared = get_uvarint(data, &pos);
        uint64_t vlen = get_uvarint(data, &pos);
        if (shared) memcpy(keys + kpos, prev_key, shared);
        memcpy(keys + kpos + shared, data + pos, unshared);
        pos += unshared;
        prev_key = keys + kpos;
        kpos += shared + unshared;
        koff[i + 1] = kpos;
        memcpy(vals + vpos, data + pos, vlen);
        pos += vlen;
        vpos += vlen;
        voff[i + 1] = vpos;
    }
    (void)len;
}

// --------------------------------------------------------------------------
// Bloom filter (double hashing, matches storage/sst.py::BloomFilter)
// --------------------------------------------------------------------------
void bloom_build(const uint64_t* hashes, int64_t n, uint8_t* bits,
                 int64_t nbits, int32_t k) {
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h1 = hashes[i];
        uint64_t h2 = (h1 >> 33) | 1ULL;
        for (int32_t j = 0; j < k; ++j) {
            uint64_t idx = (h1 + (uint64_t)j * h2) % (uint64_t)nbits;
            bits[idx >> 3] |= (uint8_t)(1u << (idx & 7));
        }
    }
}

void bloom_probe(const uint64_t* hashes, int64_t n, const uint8_t* bits,
                 int64_t nbits, int32_t k, uint8_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h1 = hashes[i];
        uint64_t h2 = (h1 >> 33) | 1ULL;
        uint8_t hit = 1;
        for (int32_t j = 0; j < k && hit; ++j) {
            uint64_t idx = (h1 + (uint64_t)j * h2) % (uint64_t)nbits;
            hit = (bits[idx >> 3] >> (idx & 7)) & 1;
        }
        out[i] = hit;
    }
}

// --------------------------------------------------------------------------
// K-way merge of sorted runs of byte keys. Runs are concatenated:
// run r covers rows [run_starts[r], run_starts[r+1]). Keys via
// (keys, offsets) like fnv64_batch. Emits the global row indices in merged
// order, skipping exact duplicates after the first (earlier run wins; pass
// runs newest-first). Returns count emitted.
// --------------------------------------------------------------------------
struct HeapItem {
    const uint8_t* key;
    uint64_t klen;
    int32_t run;
    int64_t row;     // global row index
};

static int key_cmp(const uint8_t* a, uint64_t alen, const uint8_t* b,
                   uint64_t blen) {
    size_t lim = alen < blen ? alen : blen;
    int c = memcmp(a, b, lim);
    if (c) return c;
    return alen < blen ? -1 : (alen > blen ? 1 : 0);
}

struct HeapCmp {
    bool operator()(const HeapItem& a, const HeapItem& b) const {
        int c = key_cmp(a.key, a.klen, b.key, b.klen);
        if (c) return c > 0;          // min-heap by key
        return a.run > b.run;         // tie: lower run index first (newest)
    }
};

int64_t kway_merge(const uint8_t* keys, const uint64_t* offsets,
                   const int64_t* run_starts, int32_t num_runs,
                   int64_t* out_indices, uint8_t* out_dup) {
    std::priority_queue<HeapItem, std::vector<HeapItem>, HeapCmp> heap;
    std::vector<int64_t> cursor(num_runs);
    for (int32_t r = 0; r < num_runs; ++r) {
        cursor[r] = run_starts[r];
        if (cursor[r] < run_starts[r + 1]) {
            heap.push({keys + offsets[cursor[r]],
                       offsets[cursor[r] + 1] - offsets[cursor[r]], r,
                       cursor[r]});
        }
    }
    int64_t emitted = 0;
    const uint8_t* last_key = nullptr;
    uint64_t last_len = 0;
    while (!heap.empty()) {
        HeapItem it = heap.top();
        heap.pop();
        bool dup = last_key &&
                   key_cmp(it.key, it.klen, last_key, last_len) == 0;
        out_indices[emitted] = it.row;
        out_dup[emitted] = dup ? 1 : 0;
        ++emitted;
        last_key = it.key;
        last_len = it.klen;
        int32_t r = it.run;
        if (++cursor[r] < run_starts[r + 1]) {
            heap.push({keys + offsets[cursor[r]],
                       offsets[cursor[r] + 1] - offsets[cursor[r]], r,
                       cursor[r]});
        }
    }
    return emitted;
}

// --------------------------------------------------------------------------
// Row gather / gather-scatter. The compaction pipeline's encode stage
// moves ~100 bytes/row from source blocks into merged-order output
// buffers; doing the row memcpys here keeps that stage off the GIL so
// it genuinely overlaps the merge and write stages.
// --------------------------------------------------------------------------
// Fixed-size element loops (memcpy of a compile-time size lowers to a
// single unaligned load/store — sources can be unaligned mmap views, so
// typed pointer casts would be UB). A per-row variable-size memcpy call
// is ~3x slower at 8 bytes. Macro instead of a template: this block has
// C linkage.
#define YB_GATHER_W(W)                                                  \
    for (int64_t i = 0; i < n; ++i) {                                   \
        memcpy(dst + i * (W), src + idx[i] * (W), (W));                 \
    }                                                                   \
    return;

#define YB_GS_W(W)                                                      \
    for (int64_t i = 0; i < n; ++i) {                                   \
        memcpy(dst + dst_idx[i] * (W), src + src_idx[i] * (W), (W));    \
    }                                                                   \
    return;

void gather_rows(const uint8_t* src, int64_t row_bytes,
                 const int64_t* idx, int64_t n, uint8_t* dst) {
    switch (row_bytes) {
        case 1: YB_GATHER_W(1)
        case 2: YB_GATHER_W(2)
        case 4: YB_GATHER_W(4)
        case 8: YB_GATHER_W(8)
        case 16: YB_GATHER_W(16)
    }
    for (int64_t i = 0; i < n; ++i) {
        memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
               (size_t)row_bytes);
    }
}

void gather_scatter_rows(const uint8_t* src, int64_t row_bytes,
                         const int64_t* src_idx, const int64_t* dst_idx,
                         int64_t n, uint8_t* dst) {
    switch (row_bytes) {
        case 1: YB_GS_W(1)
        case 2: YB_GS_W(2)
        case 4: YB_GS_W(4)
        case 8: YB_GS_W(8)
        case 16: YB_GS_W(16)
    }
    for (int64_t i = 0; i < n; ++i) {
        memcpy(dst + dst_idx[i] * row_bytes,
               src + src_idx[i] * row_bytes, (size_t)row_bytes);
    }
}

// --------------------------------------------------------------------------
// FUSED multi-column gather / gather-scatter / copy. One call moves EVERY
// output lane of a chunk (values, null masks, ht/write_id/tombstone, key
// matrix) instead of one ctypes round-trip per column: the whole
// row-marshalling loop runs GIL-free next to the data (the host-side
// near-data-processing move), so the compaction encode stage and the
// batch-formation stage genuinely overlap the merge / kernel stages on
// a 2-core host. Jobs are parallel arrays; per-job index pointers may
// alias (all columns of one segment share one permutation).
//   src_idx[j] == NULL -> identity source rows 0..n-1
//   dst_idx[j] == NULL -> dense output rows 0..n-1
// All row offsets are int64 throughout — a >2 GiB byte offset
// (row_bytes * idx) must never wrap through int32 (tests cover this).
// --------------------------------------------------------------------------
static inline void gather_one(const uint8_t* src, uint8_t* dst,
                              int64_t row_bytes, const int64_t* src_idx,
                              const int64_t* dst_idx, int64_t n) {
    if (src_idx && dst_idx) {
        switch (row_bytes) {
            case 1: YB_GS_W(1)
            case 2: YB_GS_W(2)
            case 4: YB_GS_W(4)
            case 8: YB_GS_W(8)
            case 16: YB_GS_W(16)
        }
        for (int64_t i = 0; i < n; ++i) {
            memcpy(dst + dst_idx[i] * row_bytes,
                   src + src_idx[i] * row_bytes, (size_t)row_bytes);
        }
        return;
    }
    if (src_idx) {
        const int64_t* idx = src_idx;
        switch (row_bytes) {
            case 1: YB_GATHER_W(1)
            case 2: YB_GATHER_W(2)
            case 4: YB_GATHER_W(4)
            case 8: YB_GATHER_W(8)
            case 16: YB_GATHER_W(16)
        }
        for (int64_t i = 0; i < n; ++i) {
            memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
                   (size_t)row_bytes);
        }
        return;
    }
    if (dst_idx) {      // pure scatter of a contiguous source range
        for (int64_t i = 0; i < n; ++i) {
            memcpy(dst + dst_idx[i] * row_bytes, src + i * row_bytes,
                   (size_t)row_bytes);
        }
        return;
    }
    memcpy(dst, src, (size_t)(n * row_bytes));
}

void gather_multi(const uint8_t* const* src, uint8_t* const* dst,
                  const int64_t* row_bytes,
                  const int64_t* const* src_idx,
                  const int64_t* const* dst_idx,
                  const int64_t* counts, int64_t njobs) {
    for (int64_t j = 0; j < njobs; ++j) {
        gather_one(src[j], dst[j], row_bytes[j], src_idx[j], dst_idx[j],
                   counts[j]);
    }
}

// Plain segmented copy: job j copies nbytes[j] from src[j] to dst[j].
// The batch-formation concat+pad (many blocks x many columns) becomes
// ONE GIL-free call instead of a python loop of np copies.
void copy_multi(const uint8_t* const* src, uint8_t* const* dst,
                const int64_t* nbytes, int64_t njobs) {
    for (int64_t j = 0; j < njobs; ++j) {
        memcpy(dst[j], src[j], (size_t)nbytes[j]);
    }
}

// Varlen heap gather: per output row i, copy lens[i] bytes from
// heap+src_start[i] to out+dst_start[i]. Replaces the numpy
// repeat-offsets trick, which materializes an int64 index entry (16
// bytes across src+dst) per HEAP BYTE moved.
void gather_heap(const uint8_t* heap, const int64_t* src_start,
                 const int64_t* dst_start, const int64_t* lens,
                 int64_t n, uint8_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        if (lens[i])
            memcpy(out + dst_start[i], heap + src_start[i],
                   (size_t)lens[i]);
    }
}

// Row-wise FNV-1a over a fixed-width [n, w] uint8 matrix (the key-hash
// lane of bulk-built blocks; twin of storage/columnar.fnv64_rows which
// makes w full numpy passes over the rows).
void fnv64_rows_fixed(const uint8_t* mat, int64_t n, int64_t w,
                      uint64_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h = 0xCBF29CE484222325ULL;
        const uint8_t* row = mat + i * w;
        for (int64_t j = 0; j < w; ++j) {
            h = (h ^ row[j]) * 0x100000001B3ULL;
        }
        out[i] = h;
    }
}

// --------------------------------------------------------------------------
// Near-data predicate pre-filter: AND of per-column inclusive range
// tests over ENCODED fixed-width lanes, evaluated next to the mmap'd
// SST bytes before any batch formation (the bypass reader's near-data
// processing move; reference inspiration: Taurus page-store pushdown).
// Each predicate p tests  lo <= col[i] <= hi  with NULL rows failing;
// `keep` is the conjunction across all predicates.  Loads go through
// memcpy into a local: lanes can be unaligned views straight over the
// file mapping, so typed pointer dereference would be UB (same
// discipline as the gather loops above).
// dtype codes (mirrored in storage/native_lib.py): 1=i32 2=i64 3=f32
// 4=f64 5=u32.  Integer predicates use the i64 bounds, float ones the
// f64 bounds.
// --------------------------------------------------------------------------
#define YB_PF_LOOP(T, LO, HI)                                           \
    {                                                                   \
        const uint8_t* base = (const uint8_t*)cols[p];                  \
        for (int64_t i = 0; i < n; ++i) {                               \
            T v;                                                        \
            memcpy(&v, base + i * (int64_t)sizeof(T), sizeof(T));       \
            keep[i] &= (uint8_t)((!nu || !nu[i]) &&                     \
                                 v >= (LO) && v <= (HI));               \
        }                                                               \
    }                                                                   \
    break;

void prefilter_ranges(const void* const* cols, const int64_t* dtypes,
                      const uint8_t* const* nulls,
                      const double* lo_f, const double* hi_f,
                      const int64_t* lo_i, const int64_t* hi_i,
                      int64_t npreds, int64_t n, uint8_t* keep) {
    for (int64_t i = 0; i < n; ++i) keep[i] = 1;
    for (int64_t p = 0; p < npreds; ++p) {
        const uint8_t* nu = nulls[p];
        switch (dtypes[p]) {
            case 1: YB_PF_LOOP(int32_t, lo_i[p], hi_i[p])
            case 2: YB_PF_LOOP(int64_t, lo_i[p], hi_i[p])
            case 3: YB_PF_LOOP(float, lo_f[p], hi_f[p])
            case 4: YB_PF_LOOP(double, lo_f[p], hi_f[p])
            case 5: YB_PF_LOOP(uint32_t, lo_i[p], hi_i[p])
            default:
                // unknown dtype: keep every row (the python binding
                // never sends one, but a stale .so must fail safe)
                break;
        }
    }
}

// --------------------------------------------------------------------------
// Fixed-width k-way merge over NON-CONTIGUOUS sorted segments (the
// pipelined compaction frontier: each segment is a row range of one
// decoded — possibly mmap-backed — block, so no concatenated key matrix
// ever materializes). seg_ptrs[s] points at segment s's first key;
// segment s holds seg_rows[s] keys of `width` bytes. Emits positions in
// the VIRTUAL concatenation of the segments (base[s] + row) in merged
// order, plus exact-duplicate flags; key ties prefer the lower segment
// index (earlier-activated block). Returns rows emitted.
// --------------------------------------------------------------------------
struct SegItem {
    const uint8_t* key;
    int32_t seg;
    int64_t pos;     // virtual concatenated position
    int64_t row;     // row within segment
};

int64_t kway_merge_segs(const uint8_t* const* seg_ptrs,
                        const int64_t* seg_rows, int32_t num_segs,
                        int64_t width, int64_t* out_indices,
                        uint8_t* out_dup) {
    struct Cmp {
        int64_t w;
        bool operator()(const SegItem& a, const SegItem& b) const {
            int c = memcmp(a.key, b.key, (size_t)w);
            if (c) return c > 0;          // min-heap by key
            return a.seg > b.seg;         // tie: lower segment first
        }
    };
    std::priority_queue<SegItem, std::vector<SegItem>, Cmp> heap(
        Cmp{width});
    std::vector<int64_t> base(num_segs + 1, 0);
    for (int32_t s = 0; s < num_segs; ++s) {
        base[s + 1] = base[s] + seg_rows[s];
        if (seg_rows[s] > 0) {
            heap.push({seg_ptrs[s], s, base[s], 0});
        }
    }
    int64_t emitted = 0;
    const uint8_t* last_key = nullptr;
    while (!heap.empty()) {
        SegItem it = heap.top();
        heap.pop();
        out_indices[emitted] = it.pos;
        out_dup[emitted] =
            (last_key && memcmp(it.key, last_key, (size_t)width) == 0)
            ? 1 : 0;
        ++emitted;
        last_key = it.key;
        if (it.row + 1 < seg_rows[it.seg]) {
            heap.push({it.key + width, it.seg, it.pos + 1, it.row + 1});
        }
    }
    return emitted;
}

}  // extern "C"
