#!/bin/sh
# Build both native libraries into their HOST-FINGERPRINTED paths
# (yugabyte_db_tpu/hostfp.py): .so files are compiled -march=native, so
# one built on another machine must never load here. The Python loaders
# auto-build on first import; this script forces it now and FAILS LOUD.
set -e
cd "$(dirname "$0")/.."
"${PYTHON:-python3}" - <<'PYEOF'
import sys
from yugabyte_db_tpu.storage import native_lib
from yugabyte_db_tpu.docdb import hotpath
ok1 = native_lib.available()
ok2 = hotpath.load() is not None
print("native_lib:", "ok" if ok1 else "FAILED", native_lib._SO)
if not ok1 and native_lib.last_build_error:
    print(native_lib.last_build_error, file=sys.stderr)
print("hotpath   :", "ok" if ok2 else "FAILED", hotpath._SO)
if not ok2 and hotpath.last_build_error:
    print(hotpath.last_build_error, file=sys.stderr)
sys.exit(0 if (ok1 and ok2) else 1)
PYEOF
