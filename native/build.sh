#!/bin/sh
# Build both native libraries into their HOST-FINGERPRINTED paths
# (yugabyte_db_tpu/hostfp.py): .so files built on one machine must never
# load on another (-march=native code SIGILLs on older CPUs). The Python
# loaders auto-build on first import; this script just forces it now.
set -e
cd "$(dirname "$0")/.."
python - <<'PYEOF'
from yugabyte_db_tpu.storage import native_lib
from yugabyte_db_tpu.docdb import hotpath
print("native_lib:", "ok" if native_lib.available() else "FAILED", native_lib._SO)
print("hotpath   :", "ok" if hotpath.load() else "FAILED", hotpath._SO)
PYEOF
