#!/bin/sh
# Build the native storage library (see ybtpu_native.cpp).
set -e
cd "$(dirname "$0")"
g++ -O3 -march=native -std=c++17 -shared -fPIC \
    ybtpu_native.cpp -o libybtpu_native.so
echo "built $(pwd)/libybtpu_native.so"
