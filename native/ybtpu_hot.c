/* ybtpu_hot — CPython extension for the per-op host hot path.
 *
 * Reference analog: the row materialization inside the DocDB point-read
 * path (src/yb/dockv/pg_row.cc PgTableRow::SetValue and the packed-row
 * decoders in src/yb/dockv/packed_row.cc) — the per-row work that the
 * reference does in C++ and a Python loop cannot do at OLTP rates.
 *
 * Exposes one type: Extractor. Built once per (table codec, columnar
 * block), it captures raw pointers into the block's numpy arrays (refs
 * held, buffers pinned via the buffer protocol) plus a decode plan, and
 * materializes row dicts with a single C call per point read.
 *
 * Column kinds in the plan:
 *   0 fixed-width value column   (values array + nulls array)
 *   1 varlen str value column    (ends uint32 + heap bytes + nulls)
 *   2 varlen bytes value column  (ends uint32 + heap bytes + nulls)
 *   3 fixed-width pk column      (values array, never null)
 *   4 missing column             (always None — added after version)
 * Fixed dtypes are passed as a single char: q=i64 i=i32 h=i16 b=i8
 * d=f64 f=f32 ?=bool Q=u64 I=u32.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <float.h>
#include <math.h>
#include <stdint.h>
#include <string.h>

typedef struct {
    PyObject *name;      /* interned column name */
    int kind;
    char dtype;          /* fixed kinds only */
    Py_buffer vals;      /* fixed: values; varlen: ends (uint32) */
    Py_buffer nulls;     /* null mask (uint8/bool), may be absent */
    Py_buffer heap;      /* varlen heap bytes */
    int has_vals, has_nulls, has_heap;
} ColPlan;

typedef struct {
    PyObject_HEAD
    Py_ssize_t ncols;
    Py_ssize_t nrows;
    ColPlan *cols;
} Extractor;

static void
Extractor_dealloc(Extractor *self)
{
    for (Py_ssize_t i = 0; i < self->ncols; i++) {
        ColPlan *c = &self->cols[i];
        Py_XDECREF(c->name);
        if (c->has_vals) PyBuffer_Release(&c->vals);
        if (c->has_nulls) PyBuffer_Release(&c->nulls);
        if (c->has_heap) PyBuffer_Release(&c->heap);
    }
    PyMem_Free(self->cols);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

/* new Extractor(plan, nrows) — plan: list of
 * (name:str, kind:int, dtype:str1, values_or_ends, nulls, heap) */
static PyObject *
Extractor_new(PyTypeObject *type, PyObject *args, PyObject *kwds)
{
    PyObject *plan;
    Py_ssize_t nrows;
    if (!PyArg_ParseTuple(args, "On", &plan, &nrows))
        return NULL;
    if (!PyList_Check(plan)) {
        PyErr_SetString(PyExc_TypeError, "plan must be a list");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(plan);
    Extractor *self = (Extractor *)type->tp_alloc(type, 0);
    if (!self) return NULL;
    self->nrows = nrows;
    self->ncols = 0;
    self->cols = (ColPlan *)PyMem_Calloc(n, sizeof(ColPlan));
    if (!self->cols) { Py_DECREF(self); return PyErr_NoMemory(); }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *t = PyList_GET_ITEM(plan, i);
        PyObject *name, *vals, *nulls, *heap;
        int kind;
        const char *dt;
        if (!PyArg_ParseTuple(t, "OisOOO", &name, &kind, &dt,
                              &vals, &nulls, &heap)) {
            Py_DECREF(self);
            return NULL;
        }
        ColPlan *c = &self->cols[i];
        c->name = name; Py_INCREF(name);
        c->kind = kind;
        c->dtype = dt[0] ? dt[0] : 'q';
        if (vals != Py_None) {
            if (PyObject_GetBuffer(vals, &c->vals, PyBUF_SIMPLE) < 0) {
                self->ncols = i + 1; Py_DECREF(self); return NULL;
            }
            c->has_vals = 1;
        }
        if (nulls != Py_None) {
            if (PyObject_GetBuffer(nulls, &c->nulls, PyBUF_SIMPLE) < 0) {
                self->ncols = i + 1; Py_DECREF(self); return NULL;
            }
            c->has_nulls = 1;
        }
        if (heap != Py_None) {
            if (PyObject_GetBuffer(heap, &c->heap, PyBUF_SIMPLE) < 0) {
                self->ncols = i + 1; Py_DECREF(self); return NULL;
            }
            c->has_heap = 1;
        }
        self->ncols = i + 1;
    }
    return (PyObject *)self;
}

static inline PyObject *
fixed_value(const ColPlan *c, Py_ssize_t pos)
{
    const char *p = (const char *)c->vals.buf;
    switch (c->dtype) {
    case 'q': return PyLong_FromLongLong(((const int64_t *)p)[pos]);
    case 'i': return PyLong_FromLong(((const int32_t *)p)[pos]);
    case 'h': return PyLong_FromLong(((const int16_t *)p)[pos]);
    case 'b': return PyLong_FromLong(((const int8_t *)p)[pos]);
    case 'Q': return PyLong_FromUnsignedLongLong(
                  ((const uint64_t *)p)[pos]);
    case 'I': return PyLong_FromUnsignedLong(((const uint32_t *)p)[pos]);
    case 'd': return PyFloat_FromDouble(((const double *)p)[pos]);
    case 'f': return PyFloat_FromDouble(((const float *)p)[pos]);
    case '?': {
        PyObject *r = ((const uint8_t *)p)[pos] ? Py_True : Py_False;
        Py_INCREF(r);
        return r;
    }
    default:
        PyErr_Format(PyExc_ValueError, "bad dtype %c", c->dtype);
        return NULL;
    }
}

/* core row materialization shared by extract() and PointReader */
/* want == NULL extracts every column; otherwise only columns whose
 * name is in `want` (a small tuple — identity-compare fast path makes
 * the membership test ~ns for interned names).  Projection in C keeps
 * short range scans (YCSB-E shape) from paying 10 string decodes per
 * row that the caller immediately throws away. */
static PyObject *
extract_row(Extractor *self, Py_ssize_t pos, PyObject *want)
{
    PyObject *out = _PyDict_NewPresized(self->ncols);
    if (!out) return NULL;
    for (Py_ssize_t i = 0; i < self->ncols; i++) {
        const ColPlan *c = &self->cols[i];
        PyObject *v = NULL;
        if (want) {
            int has = PySequence_Contains(want, c->name);
            if (has < 0) { Py_DECREF(out); return NULL; }
            if (!has) continue;
        }
        if (c->kind == 4 ||
            (c->has_nulls && ((const uint8_t *)c->nulls.buf)[pos])) {
            v = Py_None; Py_INCREF(v);
        } else if (c->kind == 0 || c->kind == 3) {
            v = fixed_value(c, pos);
        } else {  /* varlen: vals buffer = uint32 end offsets */
            const uint32_t *ends = (const uint32_t *)c->vals.buf;
            uint32_t lo = pos ? ends[pos - 1] : 0;
            uint32_t hi = ends[pos];
            const char *base = (const char *)c->heap.buf;
            v = (c->kind == 1)
                ? PyUnicode_DecodeUTF8(base + lo, hi - lo, "strict")
                : PyBytes_FromStringAndSize(base + lo, hi - lo);
        }
        if (!v || PyDict_SetItem(out, c->name, v) < 0) {
            Py_XDECREF(v);
            Py_DECREF(out);
            return NULL;
        }
        Py_DECREF(v);
    }
    return out;
}

/* extract(pos) -> dict */
static PyObject *
Extractor_extract(Extractor *self, PyObject *arg)
{
    Py_ssize_t pos = PyLong_AsSsize_t(arg);
    if (pos == -1 && PyErr_Occurred())
        return NULL;
    if (pos < 0 || pos >= self->nrows) {
        PyErr_Format(PyExc_IndexError, "row %zd out of range", pos);
        return NULL;
    }
    return extract_row(self, pos, NULL);
}

static PyMethodDef Extractor_methods[] = {
    {"extract", (PyCFunction)Extractor_extract, METH_O,
     "extract(pos) -> row dict"},
    {NULL}
};

static PyTypeObject ExtractorType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "ybtpu_hot.Extractor",
    .tp_basicsize = sizeof(Extractor),
    .tp_dealloc = (destructor)Extractor_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "per-(codec, block) point-read row extractor",
    .tp_methods = Extractor_methods,
    .tp_new = Extractor_new,
};

/* ---------------------------------------------------------------------
 * encode_doc_key(spec, values) -> bytes
 *
 * The DocKey prefix encoder (reference: src/yb/dockv/doc_key.cc
 * DocKey::Encode) — byte-identical to the Python
 * TableCodec.doc_key_prefix for the supported kinds. spec is built once
 * per codec: (cotable_id:i64 (-1 = none), num_hash:int, kinds:bytes,
 * descs:bytes). Kind codes: 0 int64, 1 int32, 2 double, 3 string,
 * 4 timestamp, 5 bytes. values is a tuple of per-column Python values
 * (None encodes kNull).
 */
#define VT_GROUP_END 0x03
#define VT_U16_HASH 0x08
#define VT_COTABLE 0x0A
#define VT_NULL 0x20
#define VT_INT32 0x24
#define VT_INT64 0x26
#define VT_DOUBLE 0x28
#define VT_STRING 0x2A
#define VT_TIMESTAMP 0x2C
#define VT_BYTES 0x2E
#define DESC_OFF 0x20
#define VT_NULL_DESC 0x5E

typedef struct {
    uint8_t *buf;
    Py_ssize_t len, cap;
} KeyBuf;

static int kb_reserve(KeyBuf *kb, Py_ssize_t extra)
{
    if (kb->len + extra <= kb->cap) return 0;
    Py_ssize_t ncap = kb->cap * 2 + extra + 64;
    uint8_t *nb = (uint8_t *)PyMem_Realloc(kb->buf, ncap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    kb->buf = nb; kb->cap = ncap;
    return 0;
}

static inline void kb_put(KeyBuf *kb, uint8_t b) { kb->buf[kb->len++] = b; }

/* encode one entry; returns bytes appended or -1 */
static int
encode_entry(KeyBuf *kb, int kind, int desc, PyObject *v)
{
    if (v == Py_None) {
        /* match the Python encoder: NULL pk components are unsupported
         * (it raises) — erroring here routes to the same Python error */
        PyErr_SetString(PyExc_TypeError, "NULL key component");
        return -1;
    }
    if (kind == 0 || kind == 1 || kind == 4) {          /* ints */
        int width = (kind == 1) ? 4 : 8;
        uint8_t marker = (kind == 1) ? VT_INT32
                       : (kind == 4) ? VT_TIMESTAMP : VT_INT64;
        long long x = PyLong_AsLongLong(v);
        if (x == -1 && PyErr_Occurred()) return -1;
        if (width == 4 && (x < INT32_MIN || x > INT32_MAX)) {
            /* the Python encoder raises OverflowError here; silent
             * truncation would key a DIFFERENT row */
            PyErr_SetString(PyExc_OverflowError,
                            "int32 key component out of range");
            return -1;
        }
        uint64_t biased = (width == 8)
            ? (uint64_t)x + 0x8000000000000000ULL
            : (uint64_t)(uint32_t)((int64_t)x + 0x80000000LL);
        if (kb_reserve(kb, 1 + width) < 0) return -1;
        kb_put(kb, desc ? marker + DESC_OFF : marker);
        for (int i = width - 1; i >= 0; i--) {
            uint8_t b = (uint8_t)(biased >> (8 * i));
            kb_put(kb, desc ? (uint8_t)~b : b);
        }
        return 0;
    }
    if (kind == 2) {                                     /* double */
        double dv = PyFloat_AsDouble(v);
        if (dv == -1.0 && PyErr_Occurred()) return -1;
        uint64_t bits;
        memcpy(&bits, &dv, 8);
        if (bits & 0x8000000000000000ULL) bits = ~bits;
        else bits |= 0x8000000000000000ULL;
        if (kb_reserve(kb, 9) < 0) return -1;
        kb_put(kb, desc ? VT_DOUBLE + DESC_OFF : VT_DOUBLE);
        for (int i = 7; i >= 0; i--) {
            uint8_t b = (uint8_t)(bits >> (8 * i));
            kb_put(kb, desc ? (uint8_t)~b : b);
        }
        return 0;
    }
    if (kind == 3 || kind == 5) {                        /* string/bytes */
        const char *raw;
        Py_ssize_t rn;
        if (kind == 3) {
            raw = PyUnicode_AsUTF8AndSize(v, &rn);
            if (!raw) return -1;
        } else {
            if (PyBytes_AsStringAndSize(v, (char **)&raw, &rn) < 0)
                return -1;
        }
        if (kb_reserve(kb, 1 + 2 * rn + 2) < 0) return -1;
        kb_put(kb, desc ? ((kind == 3 ? VT_STRING : VT_BYTES) + DESC_OFF)
                        : (kind == 3 ? VT_STRING : VT_BYTES));
        for (Py_ssize_t i = 0; i < rn; i++) {
            uint8_t b = (uint8_t)raw[i];
            if (b == 0) {
                kb_put(kb, desc ? 0xFF : 0x00);
                kb_put(kb, desc ? 0xFE : 0x01);
            } else {
                kb_put(kb, desc ? (uint8_t)~b : b);
            }
        }
        kb_put(kb, desc ? 0xFF : 0x00);   /* terminator \x00\x00 */
        kb_put(kb, desc ? 0xFF : 0x00);
        return 0;
    }
    PyErr_Format(PyExc_ValueError, "bad key kind %d", kind);
    return -1;
}

static int
build_doc_key(long long cotable, int num_hash, const uint8_t *kk,
              const uint8_t *dd, Py_ssize_t ncols, PyObject *values,
              KeyBuf *kb)
{
    kb->len = 0;
    if (kb_reserve(kb, 16) < 0) return -1;
    if (cotable >= 0) {
        kb_put(kb, VT_COTABLE);
        for (int i = 3; i >= 0; i--)
            kb_put(kb, (uint8_t)((uint64_t)cotable >> (8 * i)));
    }
    if (num_hash > 0) {
        /* FNV-1a over the encoded hash entries, folded to 16 bits
         * (must agree bit-for-bit with dockv/partition.py) */
        Py_ssize_t hash_at = kb->len;
        kb_put(kb, VT_U16_HASH);
        kb_put(kb, 0); kb_put(kb, 0);       /* patched below */
        Py_ssize_t h0 = kb->len;
        for (int i = 0; i < num_hash; i++) {
            if (encode_entry(kb, kk[i], dd[i],
                             PyTuple_GET_ITEM(values, i)) < 0)
                return -1;
        }
        uint64_t h = 0xCBF29CE484222325ULL;
        for (Py_ssize_t i = h0; i < kb->len; i++)
            h = (h ^ kb->buf[i]) * 0x100000001B3ULL;
        h ^= h >> 32;
        uint16_t h16 = (uint16_t)(h & 0xFFFF);
        kb->buf[hash_at + 1] = (uint8_t)(h16 >> 8);
        kb->buf[hash_at + 2] = (uint8_t)(h16 & 0xFF);
        if (kb_reserve(kb, 1) < 0) return -1;
        kb_put(kb, VT_GROUP_END);
    }
    for (Py_ssize_t i = num_hash; i < ncols; i++) {
        if (encode_entry(kb, kk[i], dd[i],
                         PyTuple_GET_ITEM(values, i)) < 0)
            return -1;
    }
    if (kb_reserve(kb, 1) < 0) return -1;
    kb_put(kb, VT_GROUP_END);
    return 0;
}

static PyObject *
py_encode_doc_key(PyObject *mod, PyObject *args)
{
    long long cotable;
    int num_hash;
    Py_buffer kinds, descs;
    PyObject *values;
    if (!PyArg_ParseTuple(args, "(Liy*y*)O", &cotable, &num_hash,
                          &kinds, &descs, &values))
        return NULL;
    PyObject *result = NULL;
    KeyBuf kb = {NULL, 0, 0};
    if (!PyTuple_Check(values)) {
        PyErr_SetString(PyExc_TypeError, "values must be a tuple");
        goto done;
    }
    if (PyTuple_GET_SIZE(values) != kinds.len ||
        PyTuple_GET_SIZE(values) != descs.len) {
        PyErr_SetString(PyExc_ValueError, "spec/values length mismatch");
        goto done;
    }
    if (build_doc_key(cotable, num_hash, (const uint8_t *)kinds.buf,
                      (const uint8_t *)descs.buf,
                      PyTuple_GET_SIZE(values), values, &kb) < 0)
        goto done;
    result = PyBytes_FromStringAndSize((const char *)kb.buf, kb.len);
done:
    PyMem_Free(kb.buf);
    PyBuffer_Release(&kinds);
    PyBuffer_Release(&descs);
    return result;
}

/* ---------------------------------------------------------------------
 * fnv64(bytes) -> int — FNV-1a 64-bit, byte-exact with
 * storage/columnar.fnv64_bytes (the doc-key hash for blooms/dedup).
 */
static PyObject *
py_fnv64(PyObject *mod, PyObject *arg)
{
    Py_buffer b;
    if (PyObject_GetBuffer(arg, &b, PyBUF_SIMPLE) < 0)
        return NULL;
    uint64_t h = 0xCBF29CE484222325ULL;
    const uint8_t *p = (const uint8_t *)b.buf;
    for (Py_ssize_t i = 0; i < b.len; i++)
        h = (h ^ p[i]) * 0x100000001B3ULL;
    PyBuffer_Release(&b);
    return PyLong_FromUnsignedLongLong(h);
}

/* ---------------------------------------------------------------------
 * bloom_may_contain(bits, k, hash) -> bool — double-hash probe scheme,
 * bit-exact with storage/sst.BloomFilter.may_contain.
 */
static PyObject *
py_bloom_may_contain(PyObject *mod, PyObject *args)
{
    Py_buffer bits;
    int k;
    unsigned long long hash;
    if (!PyArg_ParseTuple(args, "y*iK", &bits, &k, &hash))
        return NULL;
    uint64_t m = (uint64_t)bits.len * 8;
    const uint8_t *bb = (const uint8_t *)bits.buf;
    uint64_t h1 = hash, h2 = (h1 >> 33) | 1;
    int hit = 1;
    for (int i = 0; i < k; i++) {
        uint64_t idx = (h1 + (uint64_t)i * h2) % m;
        if (!((bb[idx >> 3] >> (idx & 7)) & 1)) { hit = 0; break; }
    }
    PyBuffer_Release(&bits);
    return PyBool_FromLong(hit);
}

/* ---------------------------------------------------------------------
 * BlockFinder — fused point-lookup over one columnar block: binary
 * search of the fixed-width key matrix + the MVCC newest-visible walk
 * that sst.point_find did row-at-a-time in Python (reference analog:
 * BlockBasedTable::Get + DocDB visibility seek,
 * src/yb/docdb/doc_rowwise_iterator.cc).
 *
 * find(prefix, read_ht, restart_hi) returns:
 *   (pos, ht, write_id, tomb) — newest visible version row
 *   ht_int                    — restart: version in (read_ht, restart_hi]
 *   None                      — no visible version in this block
 * restart_hi < 0 disables restart detection.
 */
typedef struct {
    PyObject_HEAD
    Py_buffer keys;      /* [n, width] uint8 rows, lexicographically sorted */
    Py_buffer ht;        /* [n] uint64 */
    Py_buffer wid;       /* [n] uint32 */
    Py_buffer tomb;      /* [n] uint8/bool */
    Py_ssize_t n, width;
    int has_bufs;
} BlockFinder;

static void
BlockFinder_dealloc(BlockFinder *self)
{
    if (self->has_bufs) {
        PyBuffer_Release(&self->keys);
        PyBuffer_Release(&self->ht);
        PyBuffer_Release(&self->wid);
        PyBuffer_Release(&self->tomb);
    }
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *
BlockFinder_new(PyTypeObject *type, PyObject *args, PyObject *kwds)
{
    PyObject *keys, *ht, *wid, *tomb;
    Py_ssize_t n, width;
    if (!PyArg_ParseTuple(args, "OOOOnn", &keys, &ht, &wid, &tomb,
                          &n, &width))
        return NULL;
    BlockFinder *self = (BlockFinder *)type->tp_alloc(type, 0);
    if (!self) return NULL;
    if (PyObject_GetBuffer(keys, &self->keys, PyBUF_SIMPLE) < 0 ||
        PyObject_GetBuffer(ht, &self->ht, PyBUF_SIMPLE) < 0 ||
        PyObject_GetBuffer(wid, &self->wid, PyBUF_SIMPLE) < 0 ||
        PyObject_GetBuffer(tomb, &self->tomb, PyBUF_SIMPLE) < 0) {
        /* release whichever succeeded */
        if (self->keys.obj) PyBuffer_Release(&self->keys);
        if (self->ht.obj) PyBuffer_Release(&self->ht);
        if (self->wid.obj) PyBuffer_Release(&self->wid);
        if (self->tomb.obj) PyBuffer_Release(&self->tomb);
        Py_TYPE(self)->tp_free((PyObject *)self);
        return NULL;
    }
    self->has_bufs = 1;
    self->n = n;
    self->width = width;
    if (self->keys.len < n * width || self->ht.len < n * 8 ||
        self->wid.len < n * 4 || self->tomb.len < n) {
        PyErr_SetString(PyExc_ValueError, "BlockFinder buffer too short");
        Py_DECREF(self);
        return NULL;
    }
    return (PyObject *)self;
}

/* in-block newest-visible walk shared by find() and PointReader.
 * Returns: 1 found (pos/ht/wid/tomb set), 2 restart (ht set),
 * 0 nothing visible here. */
static int
blockfinder_walk(BlockFinder *self, const uint8_t *pp, Py_ssize_t plen_real,
                 uint64_t read_ht, int64_t restart_hi,
                 Py_ssize_t *out_pos, uint64_t *out_ht, uint32_t *out_wid,
                 int *out_tomb)
{
    const uint8_t *keys = (const uint8_t *)self->keys.buf;
    const uint64_t *hts = (const uint64_t *)self->ht.buf;
    const uint32_t *wids = (const uint32_t *)self->wid.buf;
    const uint8_t *tombs = (const uint8_t *)self->tomb.buf;
    Py_ssize_t W = self->width, n = self->n;
    Py_ssize_t plen = plen_real < W ? plen_real : W;

    /* lower_bound over W-wide rows for the zero-padded probe: compare
     * the first plen bytes, then the probe's zero padding is <= any
     * remaining row byte, so rows equal on plen bytes are >= probe */
    Py_ssize_t lo = 0, hi = n;
    while (lo < hi) {
        Py_ssize_t mid = (lo + hi) >> 1;
        int c = memcmp(keys + mid * W, pp, plen);
        if (c < 0) lo = mid + 1;
        else hi = mid;
    }
    for (Py_ssize_t pos = lo; pos < n; pos++) {
        const uint8_t *row = keys + pos * W;
        /* rows are full keys (doc key + HT suffix), width >= prefix
         * when the block holds this doc key; a shorter matrix cannot
         * contain it */
        if (plen_real > W || memcmp(row, pp, plen_real) != 0)
            break;
        uint64_t ht = hts[pos];
        if (ht > read_ht) {
            if (restart_hi >= 0 && ht <= (uint64_t)restart_hi) {
                *out_ht = ht;
                return 2;
            }
            continue;
        }
        *out_pos = pos;
        *out_ht = ht;
        *out_wid = wids[pos];
        *out_tomb = tombs[pos] != 0;
        return 1;
    }
    return 0;
}

static PyObject *
BlockFinder_find(BlockFinder *self, PyObject *args)
{
    Py_buffer prefix;
    unsigned long long read_ht;
    long long restart_hi;
    if (!PyArg_ParseTuple(args, "y*KL", &prefix, &read_ht, &restart_hi))
        return NULL;
    Py_ssize_t pos = 0;
    uint64_t ht = 0;
    uint32_t wid = 0;
    int tomb = 0;
    int rc = blockfinder_walk(self, (const uint8_t *)prefix.buf,
                              prefix.len, read_ht, restart_hi,
                              &pos, &ht, &wid, &tomb);
    PyBuffer_Release(&prefix);
    if (rc == 2)
        return PyLong_FromUnsignedLongLong(ht);
    if (rc == 1)
        return Py_BuildValue("nKIi", pos, ht, (unsigned int)wid, tomb);
    Py_RETURN_NONE;
}

static PyMethodDef BlockFinder_methods[] = {
    {"find", (PyCFunction)BlockFinder_find, METH_VARARGS,
     "find(prefix, read_ht, restart_hi) -> (pos, ht, wid, tomb) | "
     "restart_ht | None"},
    {NULL}
};

static PyTypeObject BlockFinderType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "ybtpu_hot.BlockFinder",
    .tp_basicsize = sizeof(BlockFinder),
    .tp_dealloc = (destructor)BlockFinder_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "fused columnar-block point lookup (search + MVCC walk)",
    .tp_methods = BlockFinder_methods,
    .tp_new = BlockFinder_new,
};

/* ---------------------------------------------------------------------
 * Packer — packed-row V2 encoder (reference: dockv/packed_row.h
 * RowPackerV2), the per-row write hot path: null bitmap + fixed-width
 * region + varlen end-offsets + heap, assembled in one C pass from the
 * {col_id: value} dict. Built once per SchemaPacking.
 *
 * Packer(header, plan, bitmap_size, fixed_size, nvar) with plan =
 * [(id:int, kind:int, fmt:str1, off:int)] over all columns in bitmap
 * order; kind 0 = fixed (fmt one of q i h d f ?), 1 = varlen str,
 * 2 = varlen bytes.
 */
typedef struct {
    PyObject *id;        /* boxed column id for dict lookup */
    int kind;
    char fmt;
    int off;             /* fixed region offset */
} PackCol;

typedef struct {
    PyObject_HEAD
    Py_ssize_t ncols, nvar;
    Py_ssize_t bitmap_size, fixed_size;
    PyObject *header;    /* bytes */
    PackCol *cols;
} Packer;

static void
Packer_dealloc(Packer *self)
{
    for (Py_ssize_t i = 0; i < self->ncols; i++)
        Py_XDECREF(self->cols[i].id);
    PyMem_Free(self->cols);
    Py_XDECREF(self->header);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *
Packer_new(PyTypeObject *type, PyObject *args, PyObject *kwds)
{
    PyObject *header, *plan;
    Py_ssize_t bitmap_size, fixed_size, nvar;
    if (!PyArg_ParseTuple(args, "SOnnn", &header, &plan, &bitmap_size,
                          &fixed_size, &nvar))
        return NULL;
    if (!PyList_Check(plan)) {
        PyErr_SetString(PyExc_TypeError, "plan must be a list");
        return NULL;
    }
    Packer *self = (Packer *)type->tp_alloc(type, 0);
    if (!self) return NULL;
    self->ncols = 0;          /* set only once cols is allocated —
                               * dealloc walks cols up to ncols */
    self->nvar = nvar;
    self->bitmap_size = bitmap_size;
    self->fixed_size = fixed_size;
    self->header = header; Py_INCREF(header);
    self->cols = (PackCol *)PyMem_Calloc(PyList_GET_SIZE(plan),
                                         sizeof(PackCol));
    if (!self->cols) { Py_DECREF(self); return PyErr_NoMemory(); }
    self->ncols = PyList_GET_SIZE(plan);
    for (Py_ssize_t i = 0; i < self->ncols; i++) {
        long id_, kind, off;
        const char *fmt;
        if (!PyArg_ParseTuple(PyList_GET_ITEM(plan, i), "llsl",
                              &id_, &kind, &fmt, &off)) {
            Py_DECREF(self);
            return NULL;
        }
        self->cols[i].id = PyLong_FromLong(id_);
        self->cols[i].kind = (int)kind;
        self->cols[i].fmt = fmt[0];
        self->cols[i].off = (int)off;
        if (!self->cols[i].id) { Py_DECREF(self); return NULL; }
    }
    return (PyObject *)self;
}

static int
pack_fixed(uint8_t *dst, char fmt, PyObject *v)
{
    if (fmt == 'd' || fmt == 'f') {
        double dv = PyFloat_AsDouble(v);
        if (dv == -1.0 && PyErr_Occurred()) return -1;
        if (fmt == 'd') memcpy(dst, &dv, 8);
        else {
            if (isfinite(dv) && (dv > FLT_MAX || dv < -FLT_MAX)) {
                /* struct.pack('<f') semantics: finite doubles past the
                 * f32 range fail loudly, never silently become inf */
                PyErr_SetString(PyExc_OverflowError,
                                "float too large for float32 column");
                return -1;
            }
            float fv = (float)dv;
            memcpy(dst, &fv, 4);
        }
        return 0;
    }
    if (fmt == '?') {
        int b = PyObject_IsTrue(v);
        if (b < 0) return -1;
        *dst = (uint8_t)b;
        return 0;
    }
    PyObject *ix = PyNumber_Index(v);   /* struct-module semantics */
    if (!ix) return -1;
    long long x = PyLong_AsLongLong(ix);
    Py_DECREF(ix);
    if (x == -1 && PyErr_Occurred()) return -1;
    switch (fmt) {
    case 'q': memcpy(dst, &x, 8); return 0;
    case 'i': {
        if (x < INT32_MIN || x > INT32_MAX) goto range;
        int32_t y = (int32_t)x; memcpy(dst, &y, 4); return 0;
    }
    case 'h': {
        if (x < INT16_MIN || x > INT16_MAX) goto range;
        int16_t y = (int16_t)x; memcpy(dst, &y, 2); return 0;
    }
    default:
        PyErr_Format(PyExc_ValueError, "bad pack fmt %c", fmt);
        return -1;
    }
range:
    PyErr_SetString(PyExc_OverflowError, "value out of column range");
    return -1;
}

static PyObject *
Packer_pack(Packer *self, PyObject *values)
{
    if (!PyDict_Check(values)) {
        PyErr_SetString(PyExc_TypeError, "values must be a dict");
        return NULL;
    }
    Py_ssize_t hlen = PyBytes_GET_SIZE(self->header);
    /* declarations up front: the error paths jump over them (g++
     * rejects a goto crossing initializations) */
    const char **vp = NULL;
    Py_ssize_t *vl = NULL;
    Py_buffer *vbufs = NULL;            /* held buffer-protocol views */
    uint8_t *fixed_scratch = NULL;
    Py_ssize_t heap_len = 0, vi = 0, total, heap_pos, nheld = 0;
    PyObject *out = NULL;
    uint8_t *buf, *bitmap, *fixed, *ends, *heap;
    uint8_t bitmap_scratch[64];
    if (self->bitmap_size > (Py_ssize_t)sizeof(bitmap_scratch)) {
        PyErr_SetString(PyExc_ValueError, "too many columns");
        return NULL;
    }
    memset(bitmap_scratch, 0, sizeof(bitmap_scratch));
    if (self->nvar) {
        vp = (const char **)PyMem_Malloc(self->nvar * sizeof(char *));
        vl = (Py_ssize_t *)PyMem_Malloc(
            self->nvar * sizeof(Py_ssize_t));
        vbufs = (Py_buffer *)PyMem_Calloc(self->nvar,
                                          sizeof(Py_buffer));
        if (!vp || !vl || !vbufs) {
            PyMem_Free(vp); PyMem_Free(vl); PyMem_Free(vbufs);
            return PyErr_NoMemory();
        }
    }
    if (self->fixed_size) {
        fixed_scratch = (uint8_t *)PyMem_Calloc(1, self->fixed_size);
        if (!fixed_scratch) {
            PyMem_Free(vp); PyMem_Free(vl); PyMem_Free(vbufs);
            return PyErr_NoMemory();
        }
    }
    /* pass 1 does ALL value conversion — including fixed columns,
     * whose __index__/__float__ may run arbitrary Python — so the
     * cached varlen pointers can't be invalidated afterwards; held
     * buffer views pin non-bytes sources (bytearray/memoryview) */
    for (Py_ssize_t i = 0; i < self->ncols; i++) {
        PackCol *c = &self->cols[i];
        PyObject *v = PyDict_GetItem(values, c->id);   /* borrowed */
        if (v == NULL || v == Py_None) {
            bitmap_scratch[i >> 3] |= (uint8_t)(1 << (i & 7));
            if (c->kind != 0) { vp[vi] = NULL; vl[vi] = 0; vi++; }
            continue;
        }
        if (c->kind == 0) {
            if (pack_fixed(fixed_scratch + c->off, c->fmt, v) < 0)
                goto fail;
            continue;
        }
        if (PyUnicode_Check(v)) {
            Py_ssize_t n = 0;
            const char *p = PyUnicode_AsUTF8AndSize(v, &n);
            if (!p) goto fail;
            vp[vi] = p; vl[vi] = n;
        } else if (PyBytes_Check(v)) {
            vp[vi] = PyBytes_AS_STRING(v);
            vl[vi] = PyBytes_GET_SIZE(v);
        } else if (PyObject_CheckBuffer(v)) {
            /* bytearray / memoryview / numpy bytes — pinned until the
             * copy completes (matches the Python packer's bytes(v)) */
            if (PyObject_GetBuffer(v, &vbufs[vi], PyBUF_SIMPLE) < 0)
                goto fail;
            nheld = vi + 1;
            vp[vi] = (const char *)vbufs[vi].buf;
            vl[vi] = vbufs[vi].len;
        } else {
            PyErr_SetString(PyExc_TypeError,
                            "varlen column value must be str or "
                            "bytes-like");
            goto fail;
        }
        heap_len += vl[vi];
        vi++;
    }
    if (heap_len > (Py_ssize_t)UINT32_MAX) {
        PyErr_SetString(PyExc_OverflowError,
                        "packed-row heap exceeds uint32 offsets");
        goto fail;
    }
    total = hlen + self->bitmap_size + self->fixed_size
        + 4 * self->nvar + heap_len;
    out = PyBytes_FromStringAndSize(NULL, total);
    if (!out) goto fail;
    /* pass 2: pure memcpy assembly — no Python re-entry */
    buf = (uint8_t *)PyBytes_AS_STRING(out);
    memcpy(buf, PyBytes_AS_STRING(self->header), hlen);
    bitmap = buf + hlen;
    memcpy(bitmap, bitmap_scratch, self->bitmap_size);
    fixed = bitmap + self->bitmap_size;
    if (self->fixed_size)
        memcpy(fixed, fixed_scratch, self->fixed_size);
    ends = fixed + self->fixed_size;
    heap = ends + 4 * self->nvar;
    heap_pos = 0;
    for (vi = 0; vi < self->nvar; vi++) {
        if (vl[vi]) {
            memcpy(heap + heap_pos, vp[vi], vl[vi]);
            heap_pos += vl[vi];
        }
        uint32_t e = (uint32_t)heap_pos;
        memcpy(ends + 4 * vi, &e, 4);
    }
    for (Py_ssize_t i = 0; i < nheld; i++)
        if (vbufs[i].obj) PyBuffer_Release(&vbufs[i]);
    PyMem_Free(vp); PyMem_Free(vl); PyMem_Free(vbufs);
    PyMem_Free(fixed_scratch);
    return out;
fail:
    for (Py_ssize_t i = 0; i < nheld; i++)
        if (vbufs[i].obj) PyBuffer_Release(&vbufs[i]);
    PyMem_Free(vp); PyMem_Free(vl); PyMem_Free(vbufs);
    PyMem_Free(fixed_scratch);
    Py_XDECREF(out);
    return NULL;
}

static PyMethodDef Packer_methods[] = {
    {"pack", (PyCFunction)Packer_pack, METH_O,
     "pack({col_id: value}) -> packed row bytes (header included)"},
    {NULL}
};

static PyTypeObject PackerType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "ybtpu_hot.Packer",
    .tp_basicsize = sizeof(Packer),
    .tp_dealloc = (destructor)Packer_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "packed-row V2 encoder",
    .tp_methods = Packer_methods,
    .tp_new = Packer_new,
};

/* ---------------------------------------------------------------------
 * PointReader — whole-SST batched point lookup: bloom probe + block
 * bisect + the BlockFinder walk + Extractor row materialization for a
 * LIST of encoded doc-key prefixes in ONE C call (reference analog:
 * MultiGet batching over BlockBasedTable::Get,
 * src/yb/rocksdb/db/db_impl.cc, driven by pggate operation buffering,
 * src/yb/yql/pggate/pg_operation_buffer.cc).
 *
 * find_many(prefixes, read_ht, restart_hi) returns a list, one entry
 * per prefix:
 *   (ht, wid, dict|None) — newest visible version in this SST (dict is
 *                          None for a tombstone: it must still win the
 *                          cross-SST merge)
 *   int                  — restart: a version in (read_ht, restart_hi]
 *   None                 — no visible version in this SST
 *   NotImplemented       — this key needs the Python path here (block
 *                          without a finder/extractor)
 */
typedef struct {
    PyObject_HEAD
    Py_ssize_t nblocks;
    PyObject *firsts;       /* tuple of bytes (owned) */
    PyObject *lasts;        /* tuple of bytes (owned) */
    PyObject *finders;      /* tuple of BlockFinder|None (owned) */
    PyObject *extractors;   /* tuple of Extractor|None (owned) */
    Py_buffer bloom;        /* bloom bit array; absent when bloom_k==0 */
    int bloom_k;
    int has_bloom;
} PointReader;

static void
PointReader_dealloc(PointReader *self)
{
    Py_XDECREF(self->firsts);
    Py_XDECREF(self->lasts);
    Py_XDECREF(self->finders);
    Py_XDECREF(self->extractors);
    if (self->has_bloom) PyBuffer_Release(&self->bloom);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

/* PointReader(firsts, lasts, finders, extractors, bloom_bits|None, k) */
static PyObject *
PointReader_new(PyTypeObject *type, PyObject *args, PyObject *kwds)
{
    PyObject *firsts, *lasts, *finders, *extractors, *bloom;
    int k;
    if (!PyArg_ParseTuple(args, "OOOOOi", &firsts, &lasts, &finders,
                          &extractors, &bloom, &k))
        return NULL;
    if (!PyTuple_Check(firsts) || !PyTuple_Check(lasts) ||
        !PyTuple_Check(finders) || !PyTuple_Check(extractors)) {
        PyErr_SetString(PyExc_TypeError, "expected tuples");
        return NULL;
    }
    Py_ssize_t n = PyTuple_GET_SIZE(firsts);
    if (PyTuple_GET_SIZE(lasts) != n || PyTuple_GET_SIZE(finders) != n ||
        PyTuple_GET_SIZE(extractors) != n) {
        PyErr_SetString(PyExc_ValueError, "length mismatch");
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        if (!PyBytes_Check(PyTuple_GET_ITEM(firsts, i)) ||
            !PyBytes_Check(PyTuple_GET_ITEM(lasts, i))) {
            PyErr_SetString(PyExc_TypeError, "keys must be bytes");
            return NULL;
        }
        PyObject *f = PyTuple_GET_ITEM(finders, i);
        PyObject *e = PyTuple_GET_ITEM(extractors, i);
        if ((f != Py_None && !PyObject_TypeCheck(f, &BlockFinderType)) ||
            (e != Py_None && !PyObject_TypeCheck(e, &ExtractorType))) {
            PyErr_SetString(PyExc_TypeError,
                            "finders/extractors type mismatch");
            return NULL;
        }
    }
    PointReader *self = (PointReader *)type->tp_alloc(type, 0);
    if (!self) return NULL;
    self->nblocks = n;
    self->firsts = firsts; Py_INCREF(firsts);
    self->lasts = lasts; Py_INCREF(lasts);
    self->finders = finders; Py_INCREF(finders);
    self->extractors = extractors; Py_INCREF(extractors);
    self->bloom_k = k;
    self->has_bloom = 0;
    if (bloom != Py_None && k > 0) {
        if (PyObject_GetBuffer(bloom, &self->bloom, PyBUF_SIMPLE) < 0) {
            Py_DECREF(self);
            return NULL;
        }
        self->has_bloom = 1;
    }
    return (PyObject *)self;
}

/* bytes-vs-prefix lexicographic compare (memcmp + length tiebreak) */
static inline int
bytes_cmp(const uint8_t *a, Py_ssize_t an, const uint8_t *b, Py_ssize_t bn)
{
    Py_ssize_t m = an < bn ? an : bn;
    int c = memcmp(a, b, m);
    if (c) return c;
    return (an > bn) - (an < bn);
}

/* one key through this SST; returns new ref or NULL on error */
static PyObject *
pointreader_find_one(PointReader *self, const uint8_t *pp, Py_ssize_t plen,
                     uint64_t read_ht, int64_t restart_hi, PyObject *want)
{
    if (self->has_bloom) {
        uint64_t h = 0xCBF29CE484222325ULL;
        for (Py_ssize_t i = 0; i < plen; i++)
            h = (h ^ pp[i]) * 0x100000001B3ULL;
        uint64_t m = (uint64_t)self->bloom.len * 8;
        const uint8_t *bb = (const uint8_t *)self->bloom.buf;
        uint64_t h2 = (h >> 33) | 1;
        for (int i = 0; i < self->bloom_k; i++) {
            uint64_t idx = (h + (uint64_t)i * h2) % m;
            if (!((bb[idx >> 3] >> (idx & 7)) & 1))
                Py_RETURN_NONE;
        }
    }
    /* bisect_right(firsts, prefix) - 1, clamped to 0 */
    Py_ssize_t lo = 0, hi = self->nblocks;
    while (lo < hi) {
        Py_ssize_t mid = (lo + hi) >> 1;
        PyObject *fk = PyTuple_GET_ITEM(self->firsts, mid);
        if (bytes_cmp((const uint8_t *)PyBytes_AS_STRING(fk),
                      PyBytes_GET_SIZE(fk), pp, plen) <= 0)
            lo = mid + 1;
        else
            hi = mid;
    }
    Py_ssize_t b = lo > 0 ? lo - 1 : 0;
    for (; b < self->nblocks; b++) {
        PyObject *fko = PyTuple_GET_ITEM(self->firsts, b);
        const uint8_t *fk = (const uint8_t *)PyBytes_AS_STRING(fko);
        Py_ssize_t fkn = PyBytes_GET_SIZE(fko);
        if (bytes_cmp(fk, fkn, pp, plen) > 0 &&
            !(fkn >= plen && memcmp(fk, pp, plen) == 0))
            Py_RETURN_NONE;      /* block starts past the doc key */
        PyObject *lko = PyTuple_GET_ITEM(self->lasts, b);
        const uint8_t *lk = (const uint8_t *)PyBytes_AS_STRING(lko);
        Py_ssize_t lkn = PyBytes_GET_SIZE(lko);
        if (bytes_cmp(lk, lkn, pp, plen) < 0)
            continue;            /* block ends before the doc key */
        PyObject *fo = PyTuple_GET_ITEM(self->finders, b);
        PyObject *eo = PyTuple_GET_ITEM(self->extractors, b);
        if (fo == Py_None || eo == Py_None) {
            Py_INCREF(Py_NotImplemented);   /* python fallback */
            return Py_NotImplemented;
        }
        Py_ssize_t pos = 0;
        uint64_t ht = 0;
        uint32_t wid = 0;
        int tomb = 0;
        int rc = blockfinder_walk((BlockFinder *)fo, pp, plen, read_ht,
                                  restart_hi, &pos, &ht, &wid, &tomb);
        if (rc == 2)
            return PyLong_FromUnsignedLongLong(ht);
        if (rc == 1) {
            PyObject *row;
            if (tomb) {
                row = Py_None; Py_INCREF(row);
            } else {
                row = extract_row((Extractor *)eo, pos, want);
                if (!row) return NULL;
            }
            PyObject *r = Py_BuildValue("KIN", ht, (unsigned int)wid,
                                        row);
            return r;
        }
        /* nothing visible here; the doc key's versions continue into
         * the next block only when they run through this block's last
         * key */
        if (lkn >= plen && memcmp(lk, pp, plen) == 0)
            continue;
        Py_RETURN_NONE;
    }
    Py_RETURN_NONE;
}

static PyObject *
PointReader_find_many(PointReader *self, PyObject *args)
{
    PyObject *prefixes;
    unsigned long long read_ht;
    long long restart_hi;
    PyObject *want = Py_None;
    if (!PyArg_ParseTuple(args, "OKL|O", &prefixes, &read_ht, &restart_hi,
                          &want))
        return NULL;
    if (want != Py_None && !PyTuple_Check(want)) {
        PyErr_SetString(PyExc_TypeError,
                        "want_cols must be a tuple or None");
        return NULL;
    }
    if (!PyList_Check(prefixes)) {
        PyErr_SetString(PyExc_TypeError, "prefixes must be a list");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(prefixes);
    PyObject *out = PyList_New(n);
    if (!out) return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *p = PyList_GET_ITEM(prefixes, i);
        if (!PyBytes_Check(p)) {
            PyErr_SetString(PyExc_TypeError, "prefix must be bytes");
            Py_DECREF(out);
            return NULL;
        }
        PyObject *r = pointreader_find_one(
            self, (const uint8_t *)PyBytes_AS_STRING(p),
            PyBytes_GET_SIZE(p), read_ht, restart_hi,
            want == Py_None ? NULL : want);
        if (!r) { Py_DECREF(out); return NULL; }
        PyList_SET_ITEM(out, i, r);
    }
    return out;
}

static PyMethodDef PointReader_methods[] = {
    {"find_many", (PyCFunction)PointReader_find_many, METH_VARARGS,
     "find_many(prefixes, read_ht, restart_hi[, want_cols]) -> list"},
    {NULL}
};

static PyTypeObject PointReaderType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "ybtpu_hot.PointReader",
    .tp_basicsize = sizeof(PointReader),
    .tp_dealloc = (destructor)PointReader_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "whole-SST batched point lookup",
    .tp_methods = PointReader_methods,
    .tp_new = PointReader_new,
};

/* ---------------------------------------------------------------------
 * range_read(spec, lo, hi, readers, read_ht, restart_hi, want_cols,
 *            mem_set) -> list
 *
 * Fused enumerated-range scan for a single-int-hash-PK table (the
 * YCSB-E shape; reference: point segments in
 * src/yb/docdb/hybrid_scan_choices.cc driving rocksdb MultiGet): for
 * every integer key in [lo, hi] this encodes the DocKey, runs the
 * bloom+bisect+MVCC point lookup against EVERY PointReader (one per
 * SST), and merges winners by (commit ht, write id) — all without
 * surfacing per-key intermediates to Python.
 *
 * Per-key results:
 *   dict  - final visible row (projected when want_cols given)
 *   None  - no visible row (absent or tombstone)
 *   (prefix, got) - the key needs Python attention:
 *       got NotImplemented -> non-columnar block, per-key slow path
 *       got int            -> read-restart hybrid time (raise)
 *       got tuple|None     -> native best; the key hit the memtable
 *                             guard set, caller merges _mem_best
 * mem_set is the single active memtable's row-prefix set (exact
 * membership, storage/memtable.py) or None when no memtable probe is
 * needed.
 */
static PyObject *
hot_range_read(PyObject *mod, PyObject *args)
{
    long long cotable, lo, hi;
    int num_hash;
    Py_buffer kinds, descs;
    PyObject *readers, *want, *mem_set;
    unsigned long long read_ht;
    long long restart_hi;
    if (!PyArg_ParseTuple(args, "(Liy*y*)LLOKLOO", &cotable, &num_hash,
                          &kinds, &descs, &lo, &hi, &readers, &read_ht,
                          &restart_hi, &want, &mem_set))
        return NULL;
    PyObject *out = NULL;
    KeyBuf kb = {NULL, 0, 0};
    Py_ssize_t nr = 0, n = 0;
    unsigned long long span = 0;
    PyObject *wc = NULL;
    if (want != Py_None && !PyTuple_Check(want)) {
        PyErr_SetString(PyExc_TypeError, "want_cols must be tuple|None");
        goto fail;
    }
    if (mem_set != Py_None && !PySet_Check(mem_set)) {
        PyErr_SetString(PyExc_TypeError, "mem_set must be a set|None");
        goto fail;
    }
    if (!PyTuple_Check(readers)) {
        PyErr_SetString(PyExc_TypeError, "readers must be a tuple");
        goto fail;
    }
    nr = PyTuple_GET_SIZE(readers);
    for (Py_ssize_t i = 0; i < nr; i++) {
        if (!PyObject_TypeCheck(PyTuple_GET_ITEM(readers, i),
                                &PointReaderType)) {
            PyErr_SetString(PyExc_TypeError, "readers[i]: PointReader");
            goto fail;
        }
    }
    if (kinds.len != 1 || descs.len != 1 || num_hash != 1) {
        PyErr_SetString(PyExc_ValueError,
                        "range_read needs a single hash key column");
        goto fail;
    }
    span = (unsigned long long)hi - (unsigned long long)lo;
    if (hi < lo || span >= 1000000ULL) {
        PyErr_SetString(PyExc_ValueError, "bad key range");
        goto fail;
    }
    n = (Py_ssize_t)(span + 1);
    out = PyList_New(n);
    if (!out) goto fail;
    wc = want == Py_None ? NULL : want;
    for (Py_ssize_t idx = 0; idx < n; idx++) {
        long long k = lo + (long long)idx;
        PyObject *kv = PyLong_FromLongLong(k);
        if (!kv) goto fail;
        PyObject *vals = PyTuple_Pack(1, kv);
        Py_DECREF(kv);
        if (!vals) goto fail;
        int erc = build_doc_key(cotable, num_hash,
                                (const uint8_t *)kinds.buf,
                                (const uint8_t *)descs.buf, 1, vals, &kb);
        Py_DECREF(vals);
        if (erc < 0) goto fail;
        const uint8_t *pp = kb.buf;
        Py_ssize_t plen = kb.len;
        PyObject *best = NULL;       /* (ht, wid, row) winner so far */
        PyObject *attention = NULL;  /* NotImplemented | restart int */
        for (Py_ssize_t r = 0; r < nr; r++) {
            PyObject *got = pointreader_find_one(
                (PointReader *)PyTuple_GET_ITEM(readers, r),
                pp, plen, read_ht, restart_hi, wc);
            if (!got) { Py_XDECREF(best); goto fail; }
            if (got == Py_None) { Py_DECREF(got); continue; }
            if (got == Py_NotImplemented || PyLong_Check(got)) {
                attention = got;
                break;
            }
            if (best == NULL) {
                best = got;
                continue;
            }
            /* compare (ht, wid) — unsigned, boxed by find_one */
            uint64_t bht = PyLong_AsUnsignedLongLong(
                PyTuple_GET_ITEM(best, 0));
            uint64_t ght = PyLong_AsUnsignedLongLong(
                PyTuple_GET_ITEM(got, 0));
            uint64_t bw = PyLong_AsUnsignedLongLong(
                PyTuple_GET_ITEM(best, 1));
            uint64_t gw = PyLong_AsUnsignedLongLong(
                PyTuple_GET_ITEM(got, 1));
            if (PyErr_Occurred()) {
                Py_DECREF(got); Py_DECREF(best); goto fail;
            }
            if (ght > bht || (ght == bht && gw > bw)) {
                Py_DECREF(best);
                best = got;
            } else {
                Py_DECREF(got);
            }
        }
        PyObject *slot;
        int mem_hit = 0;
        if (!attention && mem_set != Py_None) {
            PyObject *pb = PyBytes_FromStringAndSize((const char *)pp,
                                                     plen);
            if (!pb) { Py_XDECREF(best); goto fail; }
            mem_hit = PySet_Contains(mem_set, pb);
            if (mem_hit < 0) {
                Py_DECREF(pb); Py_XDECREF(best); goto fail;
            }
            if (mem_hit) {
                slot = PyTuple_Pack(2, pb, best ? best : Py_None);
                Py_DECREF(pb);
                Py_XDECREF(best);
                if (!slot) goto fail;
                PyList_SET_ITEM(out, idx, slot);
                continue;
            }
            Py_DECREF(pb);
        }
        if (attention) {
            Py_XDECREF(best);
            PyObject *pb = PyBytes_FromStringAndSize((const char *)pp,
                                                     plen);
            if (!pb) { Py_DECREF(attention); goto fail; }
            slot = PyTuple_Pack(2, pb, attention);
            Py_DECREF(pb);
            Py_DECREF(attention);
            if (!slot) goto fail;
        } else if (best) {
            slot = PyTuple_GET_ITEM(best, 2);   /* row dict | None */
            Py_INCREF(slot);
            Py_DECREF(best);
        } else {
            slot = Py_None;
            Py_INCREF(slot);
        }
        PyList_SET_ITEM(out, idx, slot);
    }
    PyMem_Free(kb.buf);
    PyBuffer_Release(&kinds);
    PyBuffer_Release(&descs);
    return out;
fail:
    Py_XDECREF(out);
    PyMem_Free(kb.buf);
    PyBuffer_Release(&kinds);
    PyBuffer_Release(&descs);
    return NULL;
}

static PyMethodDef hot_methods[] = {
    {"encode_doc_key", py_encode_doc_key, METH_VARARGS,
     "encode_doc_key(spec, values) -> encoded DocKey bytes"},
    {"range_read", hot_range_read, METH_VARARGS,
     "range_read(spec, lo, hi, readers, read_ht, restart_hi, want_cols,"
     " mem_set) -> per-key rows/attention list"},
    {"fnv64", py_fnv64, METH_O,
     "fnv64(bytes) -> FNV-1a 64-bit hash"},
    {"bloom_may_contain", py_bloom_may_contain, METH_VARARGS,
     "bloom_may_contain(bits, k, hash) -> bool"},
    {NULL}
};

static PyModuleDef hotmodule = {
    PyModuleDef_HEAD_INIT, "ybtpu_hot",
    "native host hot path (row extraction, key encode)", -1, hot_methods,
};

PyMODINIT_FUNC
PyInit_ybtpu_hot(void)
{
    if (PyType_Ready(&ExtractorType) < 0)
        return NULL;
    if (PyType_Ready(&BlockFinderType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&hotmodule);
    if (!m) return NULL;
    Py_INCREF(&ExtractorType);
    PyModule_AddObject(m, "Extractor", (PyObject *)&ExtractorType);
    Py_INCREF(&BlockFinderType);
    PyModule_AddObject(m, "BlockFinder", (PyObject *)&BlockFinderType);
    if (PyType_Ready(&PointReaderType) < 0)
        return NULL;
    Py_INCREF(&PointReaderType);
    PyModule_AddObject(m, "PointReader", (PyObject *)&PointReaderType);
    if (PyType_Ready(&PackerType) < 0)
        return NULL;
    Py_INCREF(&PackerType);
    PyModule_AddObject(m, "Packer", (PyObject *)&PackerType);
    return m;
}
