"""Profile the document-shredding subsystem's stage split.

`--json` prints ONE JSON object covering both sides of the docstore:

  write side   per-path lane bytes + lane-codec encoding chosen +
               presence-lane bytes (a re-shred of every SST block's
               JSON lane through docstore.shred with a stats dict),
               with the infer/shred wall split
  scan side    shredded path-predicate scan stage split (rewrite +
               attach wall, streamed batch-build vs kernel wall from
               LAST_STREAM_STATS, coverage), against the interpreted
               extractor wall on the same SSTs

Env knobs: PROFILE_DOC_ROWS (default 200000), PROFILE_ROUNDS
(default 3), PROFILE_DOC_CHUNK (streamed chunk rows, default 65536).
"""
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("YBTPU_PLATFORM", "cpu")
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def profile_json() -> dict:
    import numpy as np

    from yugabyte_db_tpu.docdb.operations import ReadRequest
    from yugabyte_db_tpu.docstore import (DOC_STATS, DOC_WRITE_STATS,
                                          LAST_DOC_STATS, shred_lanes)
    from yugabyte_db_tpu.docstore.shred import infer_paths, \
        serialize_shred
    from yugabyte_db_tpu.models.docbench import (DOC_COL,
                                                 doc_qty_query,
                                                 docs_info,
                                                 generate_docs)
    from yugabyte_db_tpu.ops.stream_scan import LAST_STREAM_STATS
    from yugabyte_db_tpu.tablet import Tablet
    from yugabyte_db_tpu.utils import flags

    n = int(os.environ.get("PROFILE_DOC_ROWS", "200000"))
    rounds = int(os.environ.get("PROFILE_ROUNDS", "3"))
    chunk = int(os.environ.get("PROFILE_DOC_CHUNK", "65536"))

    data = generate_docs(n)
    t = Tablet("docs-prof", docs_info(),
               tempfile.mkdtemp(prefix="doc-prof-"))
    t0 = time.perf_counter()
    t.bulk_load(data, block_rows=65536)
    load_s = time.perf_counter() - t0

    # --- write side: re-shred every block's JSON lane with stats ----
    lane_stats: dict = {}
    infer_s = 0.0
    shred_s = 0.0
    blocks = 0
    r = t.regular.ssts[0]
    for i in range(r.num_blocks()):
        cb = r.columnar_block(i)
        ends, heap, null = cb.varlen[DOC_COL]
        t0 = time.perf_counter()
        infer_paths(ends, heap, null)
        infer_s += time.perf_counter() - t0
        t0 = time.perf_counter()
        bufs: list = []
        serialize_shred(ends, heap, null, bufs, lane_stats)
        shred_s += time.perf_counter() - t0
        blocks += 1
    raw_json_bytes = sum(
        len(r.columnar_block(i).varlen[DOC_COL][1])
        for i in range(r.num_blocks()))
    write_side = {
        "blocks": blocks,
        "raw_json_bytes": raw_json_bytes,
        "infer_s": round(infer_s, 4),
        # serialize_shred re-runs inference internally; the pure
        # shred/encode wall is the difference
        "shred_encode_s": round(max(shred_s - infer_s, 0.0), 4),
        "per_path": lane_stats.get("shred_paths", {}),
        "lane_encodings": {
            k: v for k, v in lane_stats.get("lanes", {}).items()},
        "cumulative_write_stats": dict(DOC_WRITE_STATS),
    }

    # --- scan side: shredded vs interpreted stage split -------------
    where, aggs = doc_qty_query()
    flags.set_flag("streaming_chunk_rows", chunk)

    def req():
        return ReadRequest("docs", where=where, aggregates=aggs)

    warm = t.read(req())
    assert warm.backend == "tpu", f"fell back: {DOC_STATS}"
    shred_ts = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        t.read(req())
        shred_ts.append(time.perf_counter() - t0)
    stream = dict(LAST_STREAM_STATS)
    doc_stats = dict(LAST_DOC_STATS)
    flags.set_flag("doc_shred_enabled", False)
    try:
        t0 = time.perf_counter()
        t.read(req())
        interp_t = time.perf_counter() - t0
    finally:
        flags.REGISTRY.reset("doc_shred_enabled")
    flags.REGISTRY.reset("streaming_chunk_rows")
    shred_t = min(shred_ts)
    return {
        "rows": n, "load_s": round(load_s, 3),
        "write_side": write_side,
        "scan_side": {
            "shred_s": round(shred_t, 4),
            "interp_s": round(interp_t, 4),
            "shred_rows_per_s": round(n / shred_t, 1),
            "interp_rows_per_s": round(n / interp_t, 1),
            "shred_vs_interp": round(interp_t / shred_t, 2),
            "coverage": doc_stats.get("coverage"),
            "paths_referenced": doc_stats.get("paths"),
            "stream_build_s": stream.get("build_s"),
            "stream_kernel_s": stream.get("kernel_s"),
            "stream_chunks": stream.get("chunks"),
            "zone_blocks_pruned": stream.get("zone_blocks_pruned"),
            "key_rebuilds": stream.get("key_rebuilds"),
        },
        "fallback_reasons": dict(DOC_STATS.get("reasons", {})),
    }


def main() -> int:
    out = profile_json()
    if "--json" in sys.argv:
        print(json.dumps(out))
    else:
        print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
